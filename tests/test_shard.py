"""Control-plane sharding (core/shard.py): unit tests for the global quota
ledger / topology partitioner / cache fan-out, end-to-end sharded scheduling
through the full MockScheduler path, the epoch re-seeding storm (nodes
migrating between shards mid-flight must not orphan rows, victim tables or
in-flight binds — the test_context_storm patterns lifted to the sharded
plane), and the `shard_parity` differential oracle: the same trace through
1-shard and N-shard configurations must agree on placement quality (placed
count, packed units) with zero global quota violations.
"""
import time
import zlib

import pytest

from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.common.resource import Resource
from yunikorn_tpu.conf.schedulerconf import parse_config_map
from yunikorn_tpu.core import gate as gate_mod
from yunikorn_tpu.core import shard as shard_mod
from yunikorn_tpu.core.queues import LimitConfig, QueueConfig, QueueTree
from yunikorn_tpu.core.scheduler import CoreScheduler
from yunikorn_tpu.core.shard import (
    GlobalQuotaLedger,
    ShardCacheFanout,
    ShardedCoreScheduler,
    ShardTopologyPartitioner,
    make_core_scheduler,
    resolve_shards,
)
from yunikorn_tpu.shim.mock_scheduler import MockScheduler

CAPPED_YAML = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: capped
            resources:
              max: {vcore: 2, memory: 8Gi}
          - name: default
"""


# --------------------------------------------------------------- conf surface
def test_resolve_shards_values():
    assert resolve_shards("auto") == 1
    assert resolve_shards("") == 1
    assert resolve_shards("1") == 1
    assert resolve_shards("4") == 4
    assert resolve_shards(8) == 8
    assert resolve_shards("999") == 64      # clamped
    assert resolve_shards("bogus") == 1     # invalid -> safe single shard


def test_conf_solver_shards_validated():
    assert parse_config_map({"solver.shards": "auto"}).solver_shards == "auto"
    assert parse_config_map({"solver.shards": "4"}).solver_shards == "4"
    with pytest.raises(ValueError):
        parse_config_map({"solver.shards": "many"})
    with pytest.raises(ValueError):
        parse_config_map({"solver.shards": "0"})
    with pytest.raises(ValueError):
        parse_config_map({"solver.shards": "65"})


def test_make_core_scheduler_single_is_plain_core():
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache

    core = make_core_scheduler(SchedulerCache(), shards="auto")
    assert type(core) is CoreScheduler
    assert core.quota_ledger is None          # no ledger probes on 1 shard
    assert core.shard_label is None
    sharded = make_core_scheduler(SchedulerCache(), shards=2)
    assert isinstance(sharded, ShardedCoreScheduler)
    assert all(c.quota_ledger is sharded.ledger for c in sharded.shards)


# ------------------------------------------------------------- ledger charges
def _tree_with_limits():
    leaf = QueueConfig(
        name="q",
        max_resource=Resource({"vcore": 10, "memory": 100}),
        limits=[LimitConfig(users=["alice"],
                            max_resources=Resource({"vcore": 4})),
                LimitConfig(groups=["dev"],
                            max_resources=Resource({"vcore": 6}))])
    root = QueueConfig(name="root", parent=True, children=[leaf])
    return QueueTree(root)


def test_ledger_charges_shapes():
    tree = _tree_with_limits()
    leaf = tree.resolve("root.q", create=False)
    r = Resource({"vcore": 2, "memory": 8})
    charges = gate_mod.ledger_charges(leaf, "alice", ["dev"], r)
    ids = {c[0] for c in charges}
    assert "q|root.q" in ids                       # queue max tracker
    assert any(t.startswith("u|root.q|") for t in ids)   # alice user limit
    assert any(t.startswith("g|root.q|") for t in ids)   # dev group limit
    # unrelated user matches only the group limit it belongs to
    charges_bob = gate_mod.ledger_charges(leaf, "bob", [], r)
    assert {c[0] for c in charges_bob} == {"q|root.q"}
    # a chain with no limits anywhere charges nothing (ledger is free)
    bare = QueueTree(QueueConfig(name="root", parent=True,
                                 children=[QueueConfig(name="q")]))
    assert gate_mod.ledger_charges(
        bare.resolve("root.q", create=False), "alice", ["dev"], r) == []
    assert gate_mod.ledger_charges(None, "alice", [], r) == []


# ---------------------------------------------------------------- the ledger
def _charges(vcore=1, limit_vcore=4, tid="q|root.q"):
    return [(tid, (("vcore", limit_vcore),), (("vcore", vcore),))]


def test_ledger_reserve_confirm_release_exact():
    led = GlobalQuotaLedger()
    assert led.reserve("a", _charges(2))
    assert led.reserve("b", _charges(2))
    assert not led.reserve("c", _charges(2))     # 2+2+2 > 4: refused
    assert led.contention_retries >= 1           # b's live reservation held it
    led.commit("a", [])                          # confirms the reservation
    led.release_reservation("b")
    assert led.reserve("c", _charges(2))         # budget freed by b's release
    led.commit("c", [])
    assert led.audit() == []
    led.release("a")                             # allocation released
    assert led.reserve("d", _charges(2))
    stats = led.stats()
    assert stats["charged_keys"] == 1 and stats["reservations"] == 1


def test_ledger_commit_idempotent_and_forced_charge_audit():
    led = GlobalQuotaLedger()
    led.commit("x", _charges(3))                 # forced (no reservation)
    led.commit("x", _charges(3))                 # idempotent: no double spend
    assert led.forced_charges == 1
    assert led.audit() == []
    led.commit("y", _charges(3))                 # 3+3 > 4: forced past limit
    assert led.audit() == ["q|root.q"]           # the violation oracle trips
    led.release("y")
    assert led.audit() == []


def test_ledger_empty_charges_always_succeed():
    led = GlobalQuotaLedger()
    for i in range(100):
        assert led.reserve(f"k{i}", [])
    assert led.stats()["trackers"] == 0          # no quota -> no state at all


def test_ledger_ttl_reaps_leaked_reservations(monkeypatch):
    led = GlobalQuotaLedger()
    assert led.reserve("leak", _charges(4))
    assert not led.reserve("next", _charges(1))
    monkeypatch.setattr(shard_mod, "RESERVE_TTL_S", 0.0)
    time.sleep(0.01)
    assert led.reserve("next", _charges(1))      # expiry freed the budget
    assert led.expired == 1


# ------------------------------------------------------------ the partitioner
def test_partitioner_ici_domains_never_straddle():
    part = ShardTopologyPartitioner(4, seed=0)
    shard_of = {}
    for i in range(64):
        dom = i // 8                             # 8 nodes per ICI domain
        labels = {"topology.yunikorn.io/slice": "s0",
                  "topology.yunikorn.io/ici-domain": f"d{dom}"}
        s = part.assign(f"n{i}", labels)
        if dom in shard_of:
            assert s == shard_of[dom]            # whole domain on one shard
        shard_of[dom] = s
    counts = [0] * 4
    for s in shard_of.values():
        counts[s] += 1
    assert max(counts) - min(counts) <= 1        # domains balance by count


def test_partitioner_reseed_moves_are_deterministic():
    def build():
        p = ShardTopologyPartitioner(4, seed=0)
        for i in range(32):
            p.assign(f"n{i}", {"topology.yunikorn.io/ici-domain":
                               f"d{i // 4}"})
        return p

    p1, p2 = build(), build()
    assert p1.reseed(1) == p2.reseed(1)          # same seed -> same moves
    assert p1.domain_shard == p2.domain_shard
    # a removed domain's slot frees; unlabeled nodes are singleton domains
    p1.remove("n0")
    p1.assign("solo", None)
    assert p1.node_domain["solo"] == ("node", "solo")


# --------------------------------------------------------------- the fan-out
class _FakeCache:
    def __init__(self):
        self._dirty = (set(), set())
        self._names = []

    def node_names(self):
        return list(self._names)

    def take_dirty_nodes(self):
        d, self._dirty = self._dirty, (set(), set())
        return d


def test_fanout_multiplexes_dirty_marks():
    cache = _FakeCache()
    fan = ShardCacheFanout(cache, 2)
    cache._names = ["a", "b", "c"]
    fan.set_owner("a", 0)
    fan.set_owner("b", 1)
    cache._dirty = ({"a", "b", "c"}, {"b"})
    d0, o0 = fan.take_dirty(0)
    assert "a" in d0 and "b" not in d0           # b belongs to shard 1
    d1, o1 = fan.take_dirty(1)
    assert d1 == {"b"} and o1 == {"b"}
    # "c" was unowned: parked, flushed to its owner the moment one appears
    fan.set_owner("c", 0)
    d0b, _ = fan.take_dirty(0)
    assert "c" in d0b
    # moving a node marks BOTH sides so each syncs the membership change
    fan.set_owner("a", 1)
    assert "a" in fan.take_dirty(0)[0]
    assert "a" in fan.take_dirty(1)[0]
    assert fan.names_for(1) == ["a", "b"] or set(
        fan.names_for(1)) == {"a", "b"}


# ----------------------------------------------------------------- e2e sharded
def _pod(name, app_id, queue="root.default", cpu=500, mem=2 ** 28):
    return make_pod(
        name, cpu_milli=cpu, memory=mem,
        labels={constants.LABEL_APPLICATION_ID: app_id,
                constants.LABEL_QUEUE_NAME: queue},
        scheduler_name=constants.SCHEDULER_NAME)


def _boot(shards, queues_yaml="", **conf):
    ms = MockScheduler()
    extra = {"solver.shards": str(shards)}
    extra.update(conf)
    ms.init(queues_yaml, conf_extra=extra)
    ms.start()
    return ms


def test_sharded_e2e_binds_across_shards():
    ms = _boot(4)
    try:
        ms.add_nodes([make_node(f"n-{i}", cpu_milli=8000) for i in range(8)])
        pods = []
        for i in range(12):
            pods.append((f"app-{i % 3}",
                         ms.add_pod(_pod(f"pod-{i}", f"app-{i % 3}"))))
        for app, p in pods:
            ms.wait_for_task_state(app, p.uid, task_mod.BOUND, timeout=30)
        rep = ms.core.shard_report()
        assert rep["count"] == 4
        assert sum(s["bound"] for s in rep["shards"]) == 12
        assert sum(s["nodes"] for s in rep["shards"]) == 8
        assert ms.core.ledger.audit() == []
        # the facade surfaces must serve (REST reads these)
        assert "last_cycle" in ms.core.metrics_snapshot() or True
        assert ms.core.health_report()["live"] in (True, False)
        assert isinstance(ms.core.tracer.spans(), list)
    finally:
        ms.stop()


def test_repair_pass_places_stranded_ask():
    """An ask whose home shard owns only too-small nodes must migrate to an
    untried shard (the full-fleet repair pass) and place there."""
    ms = _boot(2)
    try:
        ms.add_nodes([make_node(f"small-{i}", cpu_milli=300)
                      for i in range(6)])
        ms.add_node(make_node("big-0", cpu_milli=16000))
        deadline = time.time() + 10
        while ms.core.fanout.owner_of("big-0") is None:
            assert time.time() < deadline
            time.sleep(0.05)
        big_shard = ms.core.fanout.owner_of("big-0")
        app_id = next(f"app-{i}" for i in range(64)
                      if zlib.crc32(f"app-{i}".encode()) % 2 != big_shard)
        p = ms.add_pod(_pod("bigpod", app_id, cpu=2000))
        ms.wait_for_task_state(app_id, p.uid, task_mod.BOUND, timeout=30)
        assert ms.get_pod_assignment(p) == "big-0"
        rep = ms.core.shard_report()["repair"]
        assert rep["migrated"] >= 1 and rep["placed"] == 1
        assert rep["in_flight"] == 0             # settled, nothing live
    finally:
        ms.stop()


def test_global_quota_exact_across_shards():
    """16 single-pod apps homed across 4 shards into a 2-vcore queue: the
    shared ledger must admit exactly 4 fleet-wide with zero violations —
    the cross-shard double-spend the ledger exists to prevent."""
    ms = _boot(4, CAPPED_YAML)
    try:
        ms.add_nodes([make_node(f"n-{i}", cpu_milli=8000) for i in range(8)])
        pods = [(f"app-{i}", ms.add_pod(_pod(f"pod-{i}", f"app-{i}",
                                             queue="root.capped")))
                for i in range(16)]
        deadline = time.time() + 25
        while time.time() < deadline:
            if sum(1 for _, p in pods if ms.get_pod_assignment(p)) >= 4:
                break
            time.sleep(0.2)
        time.sleep(2.0)                          # extra cycles must not leak
        bound = sum(1 for _, p in pods if ms.get_pod_assignment(p))
        assert bound == 4
        assert ms.core.ledger.audit() == []
        assert ms.core.obs.get("shard_quota_violations_total").value() == 0
    finally:
        ms.stop()


def test_epoch_reseed_keeps_bound_pods_and_schedules_new():
    """Nodes migrating between shards on an epoch re-seed must not orphan
    in-flight binds or DeviceRowStore/victim rows: bound pods stay bound on
    their nodes, and pods submitted after the migration still place."""
    ms = _boot(4)
    try:
        ms.add_nodes([make_node(f"n-{i}", cpu_milli=8000)
                      for i in range(12)])
        pods = [(f"a{i}", ms.add_pod(_pod(f"p{i}", f"a{i}")))
                for i in range(8)]
        for a, p in pods:
            ms.wait_for_task_state(a, p.uid, task_mod.BOUND, timeout=30)
        before = {a: ms.get_pod_assignment(p) for a, p in pods}
        moved = ms.core.reseed_epoch()
        assert moved > 0                         # the reseed actually moved
        time.sleep(0.5)
        assert {a: ms.get_pod_assignment(p) for a, p in pods} == before
        late = [(f"a{i}", ms.add_pod(_pod(f"p{i}", f"a{i}")))
                for i in range(8, 14)]
        for a, p in late:
            ms.wait_for_task_state(a, p.uid, task_mod.BOUND, timeout=30)
        # every shard's encoder sees exactly its owned fleet slice — a
        # migrated node must exist in the new owner and be gone (invalid)
        # from the old one
        for k, core in enumerate(ms.core.shards):
            owned = set(ms.core.fanout.names_for(k))
            core.encoder.sync_nodes()
            na = core.encoder.nodes
            live = {na.name_of(i) for i in range(na.capacity)
                    if na.valid[i]}
            assert owned <= live or owned == live
            for name in live:
                assert ms.core.fanout.owner_of(name) == k
        assert ms.core.shard_report()["node_migrations"] == moved
    finally:
        ms.stop()


def test_epoch_reseed_storm_with_node_churn():
    """Context-storm pattern on the sharded plane: repeated epoch re-seeds
    interleaved with node remove/re-add and pod churn must neither wedge a
    shard nor lose placements."""
    ms = _boot(2)
    try:
        ms.add_nodes([make_node(f"n-{i}", cpu_milli=8000) for i in range(6)])
        done = []
        for epoch in range(3):
            batch = [(f"storm-{epoch}-{i}",
                      ms.add_pod(_pod(f"sp-{epoch}-{i}",
                                      f"storm-{epoch}-{i}")))
                     for i in range(4)]
            for a, p in batch:
                ms.wait_for_task_state(a, p.uid, task_mod.BOUND, timeout=30)
            done.extend(batch)
            ms.core.reseed_epoch()
            # churn a node through remove/re-add mid-epoch
            victim = f"n-{epoch}"
            keep = {a: ms.get_pod_assignment(p) for a, p in done
                    if ms.get_pod_assignment(p) != victim}
            ms.cluster.delete_node(victim)
            time.sleep(0.3)
            ms.add_node(make_node(victim, cpu_milli=8000))
            time.sleep(0.3)
            for a, node in keep.items():
                p = next(p for aa, p in done if aa == a)
                assert ms.get_pod_assignment(p) == node
        rep = ms.core.shard_report()
        assert rep["epoch"] == 3
        for k, core in enumerate(ms.core.shards):
            assert core.health.report()["live"]
    finally:
        ms.stop()


# ------------------------------------------------------- shard_parity oracle
def _run_trace(shards, n_nodes=12, n_apps=8, pods_per_app=3):
    """One fixed trace through a scheduler with the given shard count;
    returns (placed_count, packed_vcore_units, ledger_violations)."""
    ms = _boot(shards, CAPPED_YAML)
    try:
        ms.add_nodes([make_node(f"n-{i}", cpu_milli=4000)
                      for i in range(n_nodes)])
        pods = []
        for a in range(n_apps):
            for j in range(pods_per_app):
                pods.append(ms.add_pod(
                    _pod(f"t-{a}-{j}", f"papp-{a}", cpu=500)))
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(ms.get_pod_assignment(p) for p in pods):
                break
            time.sleep(0.2)
        placed = sum(1 for p in pods if ms.get_pod_assignment(p))
        packed = placed * 500                    # homogeneous asks
        if isinstance(ms.core, ShardedCoreScheduler):
            violations = ms.core.ledger.audit()
        else:
            violations = []
        return placed, packed, violations
    finally:
        ms.stop()


def test_shard_parity_oracle():
    """The differential oracle the acceptance gates on: the N-shard plane
    must place >= 0.97x the single-shard plan (same trace) with zero
    global quota violations."""
    placed_1, packed_1, _ = _run_trace(1)
    placed_4, packed_4, violations = _run_trace(4)
    assert violations == []
    assert placed_4 >= 0.97 * placed_1
    assert packed_4 >= 0.97 * packed_1
    # this trace is uncontended: both planes must place everything
    assert placed_1 == placed_4 == 8 * 3


def test_single_shard_has_no_shard_surface():
    """solver.shards=1 must build the plain pre-shard CoreScheduler: no
    ledger, no shard label, no namespace — the bit-identical contract."""
    ms = _boot(1)
    try:
        assert type(ms.core) is CoreScheduler
        assert ms.core.quota_ledger is None
        assert ms.core.aot_namespace is None
        assert not hasattr(ms.core, "shard_report") or \
            type(ms.core) is not ShardedCoreScheduler
        ms.add_node(make_node("n-0", cpu_milli=4000))
        p = ms.add_pod(_pod("solo", "app-solo"))
        ms.wait_for_task_state("app-solo", p.uid, task_mod.BOUND, timeout=30)
        # the shared-registry label contract: cycle_stage_ms stays
        # single-label ("stage") on the unsharded scheduler
        hist = ms.core.obs.get("cycle_stage_ms")
        assert hist.labelnames == ("stage",)
    finally:
        ms.stop()


def test_sharded_metrics_exposed_with_shard_labels():
    ms = _boot(2)
    try:
        ms.add_nodes([make_node(f"n-{i}", cpu_milli=8000) for i in range(4)])
        pods = [(f"m-{i}", ms.add_pod(_pod(f"mp-{i}", f"m-{i}")))
                for i in range(4)]
        for a, p in pods:
            ms.wait_for_task_state(a, p.uid, task_mod.BOUND, timeout=30)
        text = ms.core.obs.expose()
        assert "yunikorn_shard_count 2" in text
        assert 'yunikorn_shard_bound_total{shard="' in text
        assert "yunikorn_shard_quota_violations_total 0" in text
        hist = ms.core.obs.get("cycle_stage_ms")
        assert hist.labelnames == ("stage", "shard")
    finally:
        ms.stop()


# ----------------------------------------------- review-pass regressions
def _front(n=2, nodes=4, cpu=8000):
    """Direct-API sharded front end + recording callback (no shim)."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node as mknode
    from yunikorn_tpu.common.si import (
        NodeAction,
        NodeInfo,
        NodeRequest,
        RegisterResourceManagerRequest,
        ResourceManagerCallback,
    )

    class Recorder(ResourceManagerCallback):
        def __init__(self):
            self.new = []
            self.released = []
            self.updated = []
            self.skipped = []
            self.release_calls = 0

        def update_allocation(self, response):
            self.new.extend(response.new)
            self.released.extend(response.released)
            if response.released:
                self.release_calls += 1

        def update_application(self, response):
            self.updated.extend(response.updated)

        def update_node(self, response):
            pass

        def predicates(self, args):
            return None

        def preemption_predicates(self, args):
            return []

        def send_event(self, events):
            pass

        def update_container_scheduling_state(self, request):
            self.skipped.append(request)

        def get_state_dump(self):
            return "{}"

    cache = SchedulerCache()
    cb = Recorder()
    front = ShardedCoreScheduler(cache, n, interval=0.05)
    front.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="t", policy_group="queues",
                                       config=""), cb)
    infos = []
    for i in range(nodes):
        node = mknode(f"fn-{i}", cpu_milli=cpu)
        cache.update_node(node)
        infos.append(NodeInfo(node_id=node.name, action=NodeAction.CREATE,
                              node=node))
    front.update_node(NodeRequest(nodes=infos))
    return front, cb


def _mk_ask(app_id, key, cpu=500, preferred=""):
    from yunikorn_tpu.common.objects import make_pod as mkpod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk

    pod = mkpod(key, cpu_milli=cpu, memory=2 ** 28)
    return AllocationAsk(allocation_key=key, application_id=app_id,
                         resource=get_pod_resource(pod), pod=pod,
                         preferred_node=preferred)


def test_cross_shard_pinned_ask_registers_guest_and_places():
    """A preferred-node ask whose node lives on a NON-home shard must route
    there with the app registered as a guest first (regression: the guest
    registration used to collide with the ask-routing map and crash)."""
    from yunikorn_tpu.common.si import (
        AddApplicationRequest,
        AllocationRequest,
        ApplicationRequest,
        UserGroupInfo,
    )

    front, cb = _front(n=2, nodes=4)
    try:
        target_node = "fn-0"
        owner = front.fanout.owner_of(target_node)
        app_id = next(f"pin-{i}" for i in range(64)
                      if zlib.crc32(f"pin-{i}".encode()) % 2 != owner)
        front.update_application(ApplicationRequest(new=[
            AddApplicationRequest(application_id=app_id,
                                  queue_name="root.default",
                                  user=UserGroupInfo(user="u"))]))
        # this used to raise AttributeError inside update_allocation
        front.update_allocation(AllocationRequest(asks=[
            _mk_ask(app_id, "pinned-1", preferred=target_node)]))
        deadline = time.time() + 15
        while not cb.new and time.time() < deadline:
            front.schedule_once()
            time.sleep(0.05)
        assert cb.new and cb.new[0].node_id == target_node
        # the guest registration landed on the owning shard
        assert app_id in front.shards[owner].partition.applications
    finally:
        front.stop()


def test_suppressed_completed_reemitted_when_repaired_alloc_releases():
    """The fleet-level completion contract: a Completed suppressed while a
    repaired allocation lived elsewhere must be RE-EMITTED when that last
    allocation releases — the shim must not wait forever."""
    import dataclasses as dc

    from yunikorn_tpu.common.si import (
        Allocation,
        AllocationRelease,
        AllocationResponse,
        ApplicationResponse,
        UpdatedApplication,
    )
    from yunikorn_tpu.common.resource import Resource as Res

    front, cb = _front(n=2, nodes=2)
    try:
        app = "fleet-app"
        alloc = Allocation(allocation_key="ra-1", application_id=app,
                           node_id="fn-0", resource=Res({"vcore": 1}))
        front._app_home[app] = 0
        # a repaired allocation committed by the NON-home shard 1
        front._note_allocations(1, AllocationResponse(new=[alloc]))
        # home shard reports Completed -> suppressed (alloc live on s1)
        resp = front._filter_app_updates(0, ApplicationResponse(updated=[
            UpdatedApplication(application_id=app, state="Completed")]))
        assert resp is None or not resp.updated
        assert not any(u.application_id == app for u in cb.updated)
        # the repaired allocation releases -> Completed re-emitted
        front._note_allocations(1, AllocationResponse(released=[
            AllocationRelease(application_id=app, allocation_key="ra-1")]))
        assert any(u.application_id == app and u.state == "Completed"
                   for u in cb.updated)
        with front._stats_mu:
            assert app not in front._suppressed_apps
    finally:
        front.stop()


def test_release_routes_to_holder_not_broadcast():
    """A release of a key with a known home/holder goes to that shard only;
    unknown keys broadcast (regression: every release used to fan out to
    all N shards)."""
    from yunikorn_tpu.common.si import (
        AddApplicationRequest,
        AllocationRelease,
        AllocationRequest,
        ApplicationRequest,
        UserGroupInfo,
    )

    front, cb = _front(n=4, nodes=4)
    try:
        calls = {k: [] for k in range(4)}
        for k, core in enumerate(front.shards):
            orig = core.update_allocation

            def spy(req, _k=k, _orig=orig):
                calls[_k].append(req)
                return _orig(req)

            core.update_allocation = spy
        app = "rel-app"
        front.update_application(ApplicationRequest(new=[
            AddApplicationRequest(application_id=app,
                                  queue_name="root.default",
                                  user=UserGroupInfo(user="u"))]))
        front.update_allocation(AllocationRequest(asks=[
            _mk_ask(app, "rk-1")]))
        home = front._home_shard(app)
        for k in calls:
            calls[k].clear()
        front.update_allocation(AllocationRequest(releases=[
            AllocationRelease(application_id=app, allocation_key="rk-1")]))
        front.flush()  # async delivery: wait for the pumps before spying
        hit = [k for k, reqs in calls.items()
               if any(r.releases for r in reqs)]
        assert hit == [home]
        # an unknown key still broadcasts (foreign/recovery residue)
        for k in calls:
            calls[k].clear()
        front.update_allocation(AllocationRequest(releases=[
            AllocationRelease(application_id="ghost",
                              allocation_key="never-seen")]))
        front.flush()
        hit = sorted(k for k, reqs in calls.items()
                     if any(r.releases for r in reqs))
        assert hit == [0, 1, 2, 3]
    finally:
        front.stop()


def test_partitioner_relabel_rejoins_new_domain():
    """A node re-registered with CHANGED topology labels must leave its old
    domain entirely (regression: stale domain_nodes/_counts entries made
    reseed() migrate the node with its OLD domain, splitting it from its
    actual ICI siblings)."""
    p = ShardTopologyPartitioner(2, seed=0)
    old = {"topology.yunikorn.io/ici-domain": "d-old"}
    new = {"topology.yunikorn.io/ici-domain": "d-new"}
    p.assign("peer", old)
    p.assign("mover", old)
    p.assign("mover", new)
    assert p.node_domain["mover"] != p.node_domain["peer"]
    old_dom = p.node_domain["peer"]
    assert "mover" not in p.domain_nodes[old_dom]
    # counts stay consistent: two live domains, one shard slot each
    assert sum(p._counts) == len(p.domain_shard) == 2
    # a reseed moves "mover" (if at all) with its NEW domain only
    moves = p.reseed(3)
    for name, (frm, to) in moves.items():
        assert p.domain_shard[p.node_domain[name]] == to


def test_rejected_and_removed_asks_do_not_leak_routing_state():
    """Rejected asks (no release ever arrives) and app removal must purge
    _asks/_ask_home/_alloc_shard — the long-lived-process leak."""
    from yunikorn_tpu.common.si import (
        AddApplicationRequest,
        AllocationRequest,
        ApplicationRequest,
        RemoveApplicationRequest,
        UserGroupInfo,
    )

    front, cb = _front(n=2, nodes=2)
    try:
        # ask for an app that was never registered -> core rejects it
        front.update_allocation(AllocationRequest(asks=[
            _mk_ask("ghost-app", "ghost-key")]))
        front.flush()  # async delivery: the rejection arrives at the pump
        with front._mu:
            assert "ghost-key" not in front._asks
            assert "ghost-key" not in front._ask_home
        # registered app: bind one pod, then remove the app
        front.update_application(ApplicationRequest(new=[
            AddApplicationRequest(application_id="leak-app",
                                  queue_name="root.default",
                                  user=UserGroupInfo(user="u"))]))
        front.update_allocation(AllocationRequest(asks=[
            _mk_ask("leak-app", "leak-1"), _mk_ask("leak-app", "leak-2")]))
        deadline = time.time() + 15
        while len(cb.new) < 2 and time.time() < deadline:
            front.schedule_once()
            time.sleep(0.05)
        assert len(cb.new) == 2
        with front._stats_mu:
            assert all(v[1] == "leak-app"
                       for v in front._alloc_shard.values())
        front.update_application(ApplicationRequest(remove=[
            RemoveApplicationRequest(application_id="leak-app")]))
        with front._mu:
            assert not front._asks and not front._ask_home
        with front._stats_mu:
            assert not front._alloc_shard
    finally:
        front.stop()
