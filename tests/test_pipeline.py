"""Pipelined scheduling cycle: equivalence with the sequential path, the
encode-overlap contract, the no-change encode fast path, and the Prometheus
stage gauges.

The equivalence tests drive the pipeline deterministically through
CoreScheduler._pipeline_tick (the exact function the run loop calls) so the
overlap window — gate+encode of wave 2 BEFORE wave 1's commit — is forced on
every run instead of left to thread timing: tick 1 dispatches wave 1; asks
for wave 2 arrive; tick 2 prepares wave 2 while wave 1 is still in flight,
then finishes wave 1 and dispatches wave 2 against the refreshed state.
Placements are compared to a sequential core run on the same event trace by
pod NAME (uids carry a process-global counter).
"""
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
from yunikorn_tpu.common.objects import TopologySpreadConstraint
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import (
    AddApplicationRequest,
    AllocationAsk,
    AllocationRelease,
    AllocationRequest,
    ApplicationRequest,
    NodeAction,
    NodeInfo,
    NodeRequest,
    RegisterResourceManagerRequest,
    TerminationType,
    UserGroupInfo,
)
from yunikorn_tpu.core.scheduler import CoreScheduler


class NullCallback:
    def __getattr__(self, name):
        return lambda *a, **k: None


class AssumingCallback(NullCallback):
    """Minimal shim stand-in: lands each new allocation in the cache (the
    AssumePod step), so the in-flight overlay drains like production."""

    def __init__(self, cache, registry):
        self.cache = cache
        self.registry = registry

    def update_allocation(self, response):
        for alloc in getattr(response, "new", []):
            pod = self.registry.get(alloc.allocation_key)
            if pod is not None:
                pod.spec.node_name = alloc.node_id
                self.cache.update_pod(pod)


def make_core(n_nodes=64, zones=0, assuming=False):
    cache = SchedulerCache()
    core = CoreScheduler(cache)
    registry = {}
    cb = AssumingCallback(cache, registry) if assuming else NullCallback()
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="pipe", policy_group="queues"),
        cb)
    nodes = make_kwok_nodes(n_nodes)
    for i, n in enumerate(nodes):
        if zones:
            n.metadata.labels["zone"] = f"z{i % zones}"
        cache.update_node(n)
    core.update_node(NodeRequest(nodes=[
        NodeInfo(node_id=n.name, action=NodeAction.CREATE) for n in nodes]))
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="app", queue_name="root.q",
        user=UserGroupInfo(user="u"))]))
    return cache, core, registry


def asks_of(pods):
    return [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p)
            for p in pods]


def allocations_by_name(core, uid_to_name):
    out = {}
    for app in core.partition.applications.values():
        for key, alloc in app.allocations.items():
            out[uid_to_name[key]] = alloc.node_id
    return out


def run_pipelined(core, cache, waves, loc=False, extra_ticks=4):
    names = {}
    for i, pods in enumerate(waves):
        if loc:
            for p in pods:
                cache.update_pod(p)
        names.update({p.uid: p.name for p in pods})
        core.update_allocation(AllocationRequest(asks=asks_of(pods)))
        core._pipeline_tick()
    for _ in range(extra_ticks + 1):
        core._pipeline_tick()
    assert core._pipeline_inflight is None
    return allocations_by_name(core, names)


def run_sequential(core, cache, waves, loc=False, extra_cycles=4):
    core.solver.pipeline = False
    names = {}
    for pods in waves:
        if loc:
            for p in pods:
                cache.update_pod(p)
        names.update({p.uid: p.name for p in pods})
        core.update_allocation(AllocationRequest(asks=asks_of(pods)))
        core.schedule_once()
    for _ in range(extra_cycles):
        core.schedule_once()
    return allocations_by_name(core, names)


def test_pipeline_equivalent_to_sequential_plain():
    def waves():
        return [make_sleep_pods(200, "app", queue="root.q", name_prefix="w1"),
                make_sleep_pods(200, "app", queue="root.q", name_prefix="w2")]

    cache, core, _ = make_core()
    pipe = run_pipelined(core, cache, waves())
    cache2, core2, _ = make_core()
    seq = run_sequential(core2, cache2, waves())
    assert pipe == seq
    assert len(pipe) == 400


def test_pipeline_equivalent_to_sequential_spread():
    """Locality counts are placement-dependent: wave 2's batch is encoded
    BEFORE wave 1 commits, so the dispatch-time delta replay (refresh_batch
    against the in-flight overlay) is what keeps the zone-spread counts — and
    therefore the placements — identical to the sequential order."""
    def waves(cache):
        out = []
        for prefix in ("s1", "s2"):
            pods = make_sleep_pods(9, "app", queue="root.q", name_prefix=prefix)
            for p in pods:
                p.metadata.labels["app"] = "red"
                p.spec.topology_spread_constraints = [TopologySpreadConstraint(
                    max_skew=1, topology_key="zone",
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"app": "red"}})]
            out.append(pods)
        return out

    cache, core, _ = make_core(n_nodes=12, zones=3)
    pipe = run_pipelined(core, cache, waves(cache), loc=True)
    cache2, core2, _ = make_core(n_nodes=12, zones=3)
    seq = run_sequential(core2, cache2, waves(cache2), loc=True)
    assert pipe == seq
    assert len(pipe) == 18
    # the spread itself must hold: 18 pods over 3 zones, skew 1
    per_zone = {}
    for node in pipe.values():
        z = int(node[len("kwok-node-"):]) % 3
        per_zone[z] = per_zone.get(z, 0) + 1
    assert max(per_zone.values()) - min(per_zone.values()) <= 1


def test_release_mid_flight_never_commits():
    """An ask released while its batch is in flight must not come back as an
    allocation at commit (the dispatch/commit pending-checks)."""
    cache, core, _ = make_core()
    pods = make_sleep_pods(8, "app", queue="root.q", name_prefix="rel")
    core.update_allocation(AllocationRequest(asks=asks_of(pods)))
    core._pipeline_tick()
    assert core._pipeline_inflight is not None
    victim = pods[0]
    core.update_allocation(AllocationRequest(releases=[AllocationRelease(
        application_id="app", allocation_key=victim.uid,
        termination_type=TerminationType.STOPPED_BY_RM)]))
    for _ in range(3):
        core._pipeline_tick()
    app = core.partition.applications["app"]
    assert victim.uid not in app.allocations
    assert len(app.allocations) == 7


def test_pipeline_solve_failure_does_not_wedge():
    """An in-flight pipelined cycle whose solve raises on EVERY degradation
    tier must be abandoned cleanly: `_pipeline_inflight` unwedged, the
    in-flight gate exclusions cleared, the failure counted — and the next
    cycle re-admits the same asks and places them."""
    import dataclasses

    cache, core, _ = make_core()
    core.supervisor.options = dataclasses.replace(
        core.supervisor.options, max_retries=0, breaker_threshold=100,
        backoff_base_s=0.001)
    pods = make_sleep_pods(8, "app", queue="root.q", name_prefix="wz")
    core.update_allocation(AllocationRequest(asks=asks_of(pods)))
    core._pipeline_tick()                     # dispatches wave 1
    assert core._pipeline_inflight is not None
    assert core._inflight_ask_keys
    # poison the MATERIALIZE of the in-flight cycle on every tier (dispatch
    # already happened; the 3 rules cover device retry + cpu + host)
    core.supervisor.faults.fail("assign", times=3)
    core._pipeline_tick()                     # finish fails -> abandon
    assert core._pipeline_inflight is None
    assert core._inflight_ask_keys == set()
    assert core._inflight_gate_seed == []
    c = core.obs.get("scheduling_cycle_failures_total")
    assert c.value(stage="solve") == 1
    # the abandon is a FAILURE to the health subsystem: the run loop reads
    # this flag and skips _note_cycle_success, so the failure streak keeps
    # counting (readiness can actually trip on repeated abandons)
    assert core._cycle_abandoned is True
    assert core._failure_streak >= 1
    app = core.partition.applications["app"]
    assert len(app.allocations) == 0
    assert len(app.pending_asks) == 8         # asks survived the abandon
    # faults exhausted: the next cycles re-admit and place everything
    core._pipeline_tick()
    core._pipeline_tick()
    assert len(app.allocations) == 8
    assert core._pipeline_inflight is None


def test_pipeline_overlap_smoke():
    """The bench-smoke contract (make bench-smoke): a small-bucket pipelined
    run must (a) engage the overlap — encode of cycle N+1 starts before the
    materialization of cycle N — (b) hit the no-change encode fast path on an
    unchanged cycle, and (c) print the per-stage split."""
    n_pods = int(os.environ.get("YK_SMOKE_PODS", 600))
    n_nodes = int(os.environ.get("YK_SMOKE_NODES", 128))
    cache, core, registry = make_core(n_nodes=n_nodes, assuming=True)
    half = n_pods // 2
    w1 = make_sleep_pods(half, "app", queue="root.q", name_prefix="sm1")
    w2 = make_sleep_pods(half, "app", queue="root.q", name_prefix="sm2")
    registry.update({p.uid: p for p in w1 + w2})
    t0 = time.time()
    core.update_allocation(AllocationRequest(asks=asks_of(w1)))
    core._pipeline_tick()
    core.update_allocation(AllocationRequest(asks=asks_of(w2)))
    core._pipeline_tick()
    core._pipeline_tick()
    wall = time.time() - t0

    # (a) overlap engaged: encode(2) started before materialize(1)
    events = {(e[0], e[1]): e for e in core._pipeline_trace}
    assert ("encode", 2) in events and ("materialize", 1) in events
    assert events[("encode", 2)][2] < events[("materialize", 1)][2], (
        "encode of cycle 2 did not start before solve 1 materialized",
        sorted(core._pipeline_trace))

    entry = core.metrics["last_cycle"]["default"]
    assert entry["pipelined"] == 1
    first_encode_ms = entry["encode_ms"]

    # (b) no-change cycle: saturate the cluster (16-core pods against 32-core
    # nodes) so a stable leftover remains pending; once the pending set stops
    # changing, the next cycle's encode must hit the batch memo (O(1)
    # instead of O(N pods))
    leftovers = make_sleep_pods(max(half, 500), "app", queue="root.q",
                                name_prefix="smx", cpu_milli=16000)
    registry.update({p.uid: p for p in leftovers})
    core.update_allocation(AllocationRequest(asks=asks_of(leftovers)))
    full_encode_ms, cached_entry = None, None
    for _ in range(10):
        core._pipeline_tick()
        entry = core.metrics["last_cycle"]["default"]
        if entry.get("encode_cached") == 1:
            cached_entry = entry
            break
        full_encode_ms = entry["encode_ms"]
    assert cached_entry is not None, core.metrics["last_cycle"]
    entry = cached_entry
    cached_encode_ms = entry["encode_ms"]

    # (c) the stage split, printed for the bench-smoke target
    bound = len(allocations_by_name(
        core, {p.uid: p.name for p in w1 + w2 + leftovers}))
    print(f"\nbench-smoke: {bound} pods placed over {n_nodes} nodes in "
          f"{wall:.2f}s wall (2-wave pipelined)")
    print(f"bench-smoke: stage split {json.dumps(entry)}")
    print(f"bench-smoke: encode_ms full={full_encode_ms} "
          f"cached={cached_encode_ms} (first wave: {first_encode_ms})")
    if full_encode_ms is not None and full_encode_ms >= 2.0:
        assert cached_encode_ms * 5 <= full_encode_ms, (
            "no-change encode did not drop >=5x", full_encode_ms,
            cached_encode_ms)


def test_pipeline_gauges_in_prometheus_text():
    from yunikorn_tpu.webapp.rest import RestServer

    cache, core, _ = make_core(n_nodes=16)
    pods = make_sleep_pods(32, "app", queue="root.q", name_prefix="pg")
    core.update_allocation(AllocationRequest(asks=asks_of(pods)))
    core._pipeline_tick()
    core._pipeline_tick()
    rest = RestServer(core, None, port=0)
    port = rest.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    finally:
        rest.stop()
    for gauge in ("yunikorn_pipeline_overlap_ratio",
                  "yunikorn_pipeline_overlap_ms",
                  "yunikorn_pipeline_encode_ms",
                  "yunikorn_pipeline_solve_ms",
                  "yunikorn_pipeline_commit_ms",
                  "yunikorn_pipeline_cycles_total"):
        assert gauge in body, (gauge, body)
    for stage in ("encode_ms", "solve_ms", "commit_ms", "overlap_ratio"):
        assert f'yunikorn_cycle_{stage}{{partition="default"}}' in body
