"""Pallas fused best-node kernel vs the XLA reference path.

Runs in interpret mode on CPU (the real-TPU lowering is exercised by bench).
Scores are quantized to 1/128 in the kernel, so equivalence is asserted on
(feasibility exactly, chosen-node score within one quantization step).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from yunikorn_tpu.models.policies import node_base_scores
from yunikorn_tpu.ops.pallas_kernels import SCORE_SCALE, pallas_best_nodes


def random_problem(rng, n=256, m=512, g=4, r=8):
    req = rng.integers(1, 100, size=(n, r)).astype(np.int32)
    gid = rng.integers(0, g, size=(n,)).astype(np.int32)
    feas = rng.random((g, m)) < 0.7
    free = rng.integers(0, 200, size=(m, r)).astype(np.int32)
    cap = free + rng.integers(1, 100, size=(m, r)).astype(np.int32)
    return req, gid, feas, free, cap


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pallas_matches_xla_reference(seed):
    rng = np.random.default_rng(seed)
    req, gid, feas, free, cap = random_problem(rng)
    scores = node_base_scores(jnp.asarray(free), jnp.asarray(cap), "binpacking")

    soft = (rng.random((feas.shape[0], free.shape[0])).astype(np.float32) - 0.5)
    best_p, feas_p = pallas_best_nodes(
        jnp.asarray(req), jnp.asarray(gid), jnp.asarray(feas),
        jnp.asarray(soft), jnp.asarray(free), scores, interpret=True)

    # dense reference
    fit = (free[None, :, :] >= req[:, None, :]).all(-1)          # [N, M]
    ok = fit & np.asarray(feas)[gid]
    q = np.round((np.asarray(scores)[None, :] + soft[gid]) * SCORE_SCALE)
    masked = np.where(ok, q, -np.inf)
    ref_feasible = ok.any(1)
    ref_best = masked.argmax(1)

    np.testing.assert_array_equal(np.asarray(feas_p), ref_feasible)
    bp = np.asarray(best_p)
    for i in range(req.shape[0]):
        if not ref_feasible[i]:
            continue
        # same quantized score and both genuinely feasible (ties may pick
        # different columns only if quantized scores are equal — the kernel
        # breaks ties toward the lowest index, argmax does too, so they match)
        assert ok[i, bp[i]], f"pod {i}: pallas chose infeasible node"
        assert masked[i, bp[i]] == masked[i, ref_best[i]], f"pod {i}: score mismatch"
        assert bp[i] == ref_best[i], f"pod {i}: tie-break mismatch"


def test_pallas_all_infeasible():
    rng = np.random.default_rng(3)
    req, gid, feas, free, cap = random_problem(rng)
    feas[:] = False
    scores = node_base_scores(jnp.asarray(free), jnp.asarray(cap), "binpacking")
    soft = np.zeros((feas.shape[0], free.shape[0]), np.float32)
    best, feasible = pallas_best_nodes(
        jnp.asarray(req), jnp.asarray(gid), jnp.asarray(feas),
        jnp.asarray(soft), jnp.asarray(free), scores, interpret=True)
    assert not np.asarray(feasible).any()


def test_solve_with_pallas_path():
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.ops.assign import solve_batch
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for i in range(16):
        cache.update_node(make_node(f"n{i}", cpu_milli=4000))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"p{i}", cpu_milli=1000, memory=2**20) for i in range(40)]
    asks = [AllocationAsk(p.uid, "a", get_pod_resource(p), pod=p) for p in pods]
    batch = enc.build_batch(asks)
    ref = solve_batch(batch, enc.nodes, chunk=64)
    pal = solve_batch(batch, enc.nodes, chunk=64, use_pallas=True, pallas_interpret=True)
    a1 = np.asarray(ref.assigned)[: batch.num_pods]
    a2 = np.asarray(pal.assigned)[: batch.num_pods]
    assert (a1 >= 0).all() and (a2 >= 0).all()
    assert (np.asarray(pal.free_after) >= 0).all()


def test_solve_with_pallas_and_soft_terms():
    """Round-2: soft taints + preferred affinity no longer disable the fused
    kernel — the combined group_soft matrix rides into it."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import (NodeSelectorRequirement,
                                             NodeSelectorTerm, Affinity,
                                             Taint, make_node, make_pod)
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.ops.assign import solve_batch
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for i in range(8):
        taints = [Taint("noisy", "1", "PreferNoSchedule")] if i < 4 else []
        cache.update_node(make_node(f"n{i}", cpu_milli=4000,
                                    labels={"tier": "gold" if i >= 6 else "std"},
                                    taints=taints))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = []
    for i in range(16):
        p = make_pod(f"p{i}", cpu_milli=500, memory=2**20)
        p.spec.affinity = Affinity(node_preferred_terms=[
            (100, NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement("tier", "In", ["gold"])]))])
        pods.append(p)
    asks = [AllocationAsk(p.uid, "a", get_pod_resource(p), pod=p) for p in pods]
    batch = enc.build_batch(asks)
    assert batch.g_pref_weight.any()  # soft terms present
    ref = solve_batch(batch, enc.nodes, chunk=64, policy="spread")
    pal = solve_batch(batch, enc.nodes, chunk=64, policy="spread",
                      use_pallas=True, pallas_interpret=True)
    a1 = np.asarray(ref.assigned)[: batch.num_pods]
    a2 = np.asarray(pal.assigned)[: batch.num_pods]
    assert (a1 >= 0).all() and (a2 >= 0).all()
    assert (np.asarray(pal.free_after) >= 0).all()

    def gold_share(assigned):
        # nodes n6/n7 carry tier=gold; the 100-weight preference must pull
        # pods there until full (16 pods × 500m over 2 × 4000m = exactly all)
        return sum(1 for idx in assigned if enc.nodes.name_of(int(idx)) in ("n6", "n7"))

    # BOTH paths must honor the soft preference — if the kernel dropped
    # group_soft, its gold share would collapse to the spread baseline
    assert gold_share(a1) == 16
    assert gold_share(a2) == 16


def test_solve_with_pallas_locality_batch():
    """Round-3: locality constraints no longer bypass the fused kernel — the
    per-round rules/scores are hoisted into the kernel's [G, M] feasibility and
    soft inputs (VERDICT r2 item 3: the old `not has_loc` gate excluded every
    affinity/spread-bearing workload). The pallas path must match the XLA path
    assignment-for-assignment and honor the locality semantics."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import (Affinity, PodAffinityTerm,
                                             TopologySpreadConstraint,
                                             make_node, make_pod)
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.ops.assign import solve_batch
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for i in range(12):
        cache.update_node(make_node(
            f"n{i}", cpu_milli=8000, memory=8 * 2**30,
            labels={"zone": f"z{i % 3}", "kubernetes.io/hostname": f"n{i}"}))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = []
    for i in range(18):  # hard spread over 3 zones
        p = make_pod(f"sp{i}", cpu_milli=400, memory=2**26)
        p.metadata.labels["grp"] = "spread"
        p.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=1, topology_key="zone", when_unsatisfiable="DoNotSchedule",
            label_selector={"matchLabels": {"grp": "spread"}})]
        pods.append(p)
    for i in range(6):   # anti-affinity: one per hostname
        p = make_pod(f"an{i}", cpu_milli=400, memory=2**26)
        p.metadata.labels["grp"] = "anti"
        p.spec.affinity = Affinity(pod_anti_affinity_required=[PodAffinityTerm(
            label_selector={"matchLabels": {"grp": "anti"}},
            topology_key="kubernetes.io/hostname")])
        pods.append(p)
    asks = [AllocationAsk(p.uid, "a", get_pod_resource(p), pod=p) for p in pods]
    batch = enc.build_batch(asks)
    assert batch.locality is not None
    ref = solve_batch(batch, enc.nodes, chunk=32)
    pal = solve_batch(batch, enc.nodes, chunk=32, use_pallas=True,
                      pallas_interpret=True)
    a1 = np.asarray(ref.assigned)[: batch.num_pods]
    a2 = np.asarray(pal.assigned)[: batch.num_pods]
    np.testing.assert_array_equal(a1, a2)
    assert (a1 >= 0).all()
    # locality semantics hold on the pallas result: spread balanced across
    # zones within maxSkew, anti pods on distinct hostnames
    zone_counts = {}
    hosts = set()
    for i, idx in enumerate(a2):
        name = enc.nodes.name_of(int(idx))
        zone = int(name[1:]) % 3
        if i < 18:
            zone_counts[zone] = zone_counts.get(zone, 0) + 1
        else:
            assert name not in hosts, "anti-affinity violated on pallas path"
            hosts.add(name)
    assert max(zone_counts.values()) - min(zone_counts.values()) <= 1


@pytest.mark.parametrize("seed", [7])
def test_pallas_no_soft_variant_matches(seed):
    """has_soft=False (no soft DMA/matmul) must equal the soft variant with a
    zero matrix."""
    rng = np.random.default_rng(seed)
    req, gid, feas, free, cap = random_problem(rng)
    scores = node_base_scores(jnp.asarray(free), jnp.asarray(cap), "binpacking")
    zeros = np.zeros((feas.shape[0], free.shape[0]), np.float32)
    b1, f1 = pallas_best_nodes(jnp.asarray(req), jnp.asarray(gid), jnp.asarray(feas),
                               jnp.asarray(zeros), jnp.asarray(free), scores,
                               interpret=True, has_soft=True)
    b2, f2 = pallas_best_nodes(jnp.asarray(req), jnp.asarray(gid), jnp.asarray(feas),
                               jnp.asarray(zeros), jnp.asarray(free), scores,
                               interpret=True, has_soft=False)
    np.testing.assert_array_equal(np.asarray(f1), np.asarray(f2))
    np.testing.assert_array_equal(np.asarray(b1), np.asarray(b2))
