"""Differential test: the incremental encoder (dirty-node sync + the
device-resident NodeArrays mirror) must stay bit-identical to a cold full
re-encode across node add/remove, schedulable flips, pod churn, and vocab
growth — the invariant the pipelined cycle's O(changes) encode rests on.

The cold reference shares the live encoder's Vocabs (all symbols are already
interned, so lookups resolve to the same bits); rows are compared by node
NAME because the two encoders may assign different row indices.
"""
import random

import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import Taint, make_node, make_pod
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.snapshot.encoder import DeviceNodeState, SnapshotEncoder

ROW_ARRAYS = ("free", "capacity_arr", "labels", "taints_hard", "taints_soft",
              "ports", "schedulable", "valid")


def _rows_by_name(enc):
    out = {}
    for name, idx in enc.nodes._name_to_idx.items():
        out[name] = {a: np.array(getattr(enc.nodes, a)[idx])
                     for a in ROW_ARRAYS}
    return out


def _assert_bit_identical(live, cache, seed, rnd):
    cold = SnapshotEncoder(cache, vocabs=live.vocabs)
    cold.sync_nodes(full=True)
    # carry the DRAIN/READY overrides — core state, not cache state
    for name, sched in live._unschedulable_overrides.items():
        cold.set_node_schedulable(name, sched)
    a, b = _rows_by_name(live), _rows_by_name(cold)
    assert set(a) == set(b), (seed, rnd, set(a) ^ set(b))
    for name in a:
        for arr in ROW_ARRAYS:
            av, bv = a[name][arr], b[name][arr]
            # the live encoder's row may be wider (stale padding beyond the
            # cold one never holds set bits for live symbols)
            w = min(av.shape[0], bv.shape[0]) if av.ndim else None
            if av.ndim == 0:
                assert av == bv, (seed, rnd, name, arr)
            else:
                assert (av[:w] == bv[:w]).all(), (seed, rnd, name, arr)
                assert not av[w:].any() and not bv[w:].any(), \
                    (seed, rnd, name, arr)
    return cold


def _assert_device_mirror(enc, seed, rnd):
    dev = enc.device_arrays()
    host = DeviceNodeState(enc.nodes)._host_views()
    for k, v in host.items():
        got = np.asarray(dev[k])
        assert got.shape == v.shape, (seed, rnd, k)
        assert (got == v).all(), (seed, rnd, k)


def _random_event(rng, cache, enc, nodes, pods, i):
    r = rng.random()
    if r < 0.25 or not nodes:
        # node add — sometimes with fresh label/taint symbols (vocab growth)
        labels = {"zone": rng.choice(["z0", "z1", "z2"])}
        if rng.random() < 0.3:
            labels[f"grow-{i}"] = f"v{i}"
        node = make_node(f"inc-n{i}", cpu_milli=rng.choice([2000, 4000]),
                         memory=8 * 2**30, labels=labels)
        if rng.random() < 0.3:
            node.spec.taints = [Taint(key=f"tk{i % 5}", value="x",
                                      effect="NoSchedule")]
        cache.update_node(node)
        nodes.append(node)
    elif r < 0.4:
        # schedulable flip through the core-facing API
        node = rng.choice(nodes)
        enc.set_node_schedulable(node.name, rng.random() < 0.5)
    elif r < 0.55 and len(nodes) > 2:
        node = nodes.pop(rng.randrange(len(nodes)))
        cache.remove_node(node.name)
        pods[:] = [p for p in pods if p.spec.node_name != node.name]
    elif r < 0.8:
        # pod churn: assigned pod lands (free-row refresh path)
        node = rng.choice(nodes)
        pod = make_pod(f"inc-p{i}", cpu_milli=rng.choice([100, 300, 700]),
                       memory=2**20, node_name=node.name, phase="Running")
        if rng.random() < 0.2:
            pod.spec.containers[0].ports = [
                {"hostPort": 9000 + rng.randint(0, 3), "protocol": "TCP"}]
        cache.update_pod(pod)
        pods.append(pod)
    elif pods:
        pod = pods.pop(rng.randrange(len(pods)))
        cache.remove_pod(pod)


@pytest.mark.parametrize("seed", range(4))
def test_incremental_encoder_matches_cold_reencode(seed):
    rng = random.Random(seed)
    cache = SchedulerCache()
    enc = SnapshotEncoder(cache)
    nodes, pods = [], []
    for rnd in range(6):
        for i in range(rng.randint(2, 8)):
            _random_event(rng, cache, enc, nodes, pods, rnd * 100 + i)
        enc.sync_nodes()   # incremental: only dirty nodes re-encode
        _assert_bit_identical(enc, cache, seed, rnd)
        _assert_device_mirror(enc, seed, rnd)


def test_incremental_and_cold_solve_identically():
    rng = random.Random(99)
    cache = SchedulerCache()
    enc = SnapshotEncoder(cache)
    nodes, pods = [], []
    for i in range(24):
        _random_event(rng, cache, enc, nodes, pods, i)
    enc.sync_nodes()
    cold = _assert_bit_identical(enc, cache, 99, -1)
    ask_pods = [make_pod(f"solve-p{i}", cpu_milli=300, memory=2**20)
                for i in range(12)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p)
            for p in ask_pods]
    res_live = solve_batch(enc.build_batch(asks), enc.nodes,
                           device_state=enc.device_arrays())
    res_cold = solve_batch(cold.build_batch(asks), cold.nodes)
    a_live = np.asarray(res_live.assigned)[: len(asks)]
    a_cold = np.asarray(res_cold.assigned)[: len(asks)]
    names_live = [enc.nodes.name_of(int(i)) if i >= 0 else None for i in a_live]
    names_cold = [cold.nodes.name_of(int(i)) if i >= 0 else None for i in a_cold]
    assert names_live == names_cold


def test_device_mirror_refresh_modes():
    """Clean cycles reuse the buffers outright; pod churn re-uploads only
    the free/ports arrays (never the wide symbol bitsets); shape growth
    re-uploads everything — the transfer-cost contract of the pipelined
    cycle."""
    cache = SchedulerCache()
    enc = SnapshotEncoder(cache)
    for i in range(4):
        cache.update_node(make_node(f"m{i}", cpu_milli=2000, memory=2**30))
    enc.sync_nodes()
    enc.device_arrays()
    assert enc.device.last_refresh == "full"
    enc.device_arrays()
    assert enc.device.last_refresh == "clean"
    pod = make_pod("mp0", cpu_milli=500, memory=2**20, node_name="m0",
                   phase="Running")
    cache.update_pod(pod)
    enc.sync_nodes()
    enc.device_arrays()
    assert enc.device.last_refresh == "fields"
    assert enc.device.last_fields == ("free_i", "ports")
    _assert_device_mirror(enc, 0, 0)
    # capacity growth (row count doubles past the 128-row floor) changes the
    # array shapes -> full re-upload, still bit-identical
    for i in range(130):
        cache.update_node(make_node(f"grow-{i}", cpu_milli=1000, memory=2**30))
    enc.sync_nodes()
    enc.device_arrays()
    assert enc.device.last_refresh == "full"
    _assert_device_mirror(enc, 0, 1)


def test_pod_batch_partial_reencode_is_o_changed():
    """Round-10 contract: a churn cycle re-derives signatures/quantization
    only for new or changed asks (the per-ask encoded-row cache serves the
    rest), and the partially-cached batch is bit-identical to a cold encode
    of the same ask list."""
    cache = SchedulerCache()
    for i in range(8):
        cache.update_node(make_node(f"pb-n{i}", cpu_milli=64000,
                                    memory=128 * 2**30))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"pb-p{i}", cpu_milli=100 + (i % 3) * 50)
            for i in range(1000)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p, seq=i)
            for i, p in enumerate(pods)]

    enc.build_batch(asks)
    assert enc.last_encode_rows == 1000
    assert enc.last_encode_rows_reencoded == 1000    # cold: everything fresh

    enc.build_batch(asks)
    assert enc.last_encode_rows_reencoded == 0       # unchanged: all cached

    # 1% churn: 10 re-submitted asks (same key, fresh seq + new resource —
    # the core's resubmission identity) plus 5 brand-new asks
    churned = list(asks)
    for i in range(10):
        p = make_pod(f"pb-p{i}", cpu_milli=900)
        churned[i] = AllocationAsk(asks[i].allocation_key, "app",
                                   get_pod_resource(p), pod=p, seq=2000 + i)
    new_pods = [make_pod(f"pb-new{i}", cpu_milli=250) for i in range(5)]
    churned.extend(AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p,
                                 seq=3000 + i)
                   for i, p in enumerate(new_pods))

    live = enc.build_batch(churned)
    assert enc.last_encode_rows == 1005
    assert enc.last_encode_rows_reencoded == 15      # O(changed), not O(pods)

    cold_enc = SnapshotEncoder(cache, vocabs=enc.vocabs)
    cold_enc.sync_nodes(full=True)
    cold = cold_enc.build_batch(churned)
    assert (live.req == cold.req).all()
    assert (live.group_id == cold.group_id).all()
    assert (live.valid == cold.valid).all()
    assert live.ask_keys == cold.ask_keys
    assert (live.g_tol == cold.g_tol).all()
    assert (live.g_term_req == cold.g_term_req).all()


def test_pod_batch_cache_floors_eviction_at_batch_size():
    """A batch larger than the LRU cap (possible on the legacy gate path,
    which has no batch ceiling) must not thrash: eviction is floored at the
    live batch size, so an unchanged repeat cycle still re-derives zero."""
    cache = SchedulerCache()
    cache.update_node(make_node("fl-n0", cpu_milli=64000, memory=128 * 2**30))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    enc._ask_row_cache_max = 8                       # force an over-cap batch
    pods = [make_pod(f"fl-p{i}", cpu_milli=100) for i in range(30)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p, seq=i)
            for i, p in enumerate(pods)]
    enc.build_batch(asks)
    assert enc.last_encode_rows_reencoded == 30
    enc.build_batch(asks)
    assert enc.last_encode_rows_reencoded == 0       # no steady-state thrash
    # stale entries (departed asks) still evict back down to the live set
    enc.build_batch(asks[:8])
    assert len(enc._ask_row_cache) == 8


def test_pod_batch_cache_invalidates_on_anti_term_churn():
    """Anti-affinity term-set churn regenerates the memoized term list; the
    per-ask cache must miss (identity key) and re-derive signatures, keeping
    locality-dependent groups exact."""
    from yunikorn_tpu.common.objects import PodAffinityTerm

    cache = SchedulerCache()
    cache.update_node(make_node("at-n0", cpu_milli=8000, memory=16 * 2**30))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"at-p{i}", cpu_milli=100, labels={"app": "web"})
            for i in range(20)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p, seq=i)
            for i, p in enumerate(pods)]
    enc.build_batch(asks)
    enc.build_batch(asks)
    assert enc.last_encode_rows_reencoded == 0
    # a cached pod carrying a new anti-affinity term bumps anti_version
    from yunikorn_tpu.common.objects import Affinity

    anti = make_pod("at-anti", cpu_milli=100)
    anti.spec.affinity = Affinity(pod_anti_affinity_required=[
        PodAffinityTerm(topology_key="kubernetes.io/hostname",
                        label_selector={"matchLabels": {"app": "web"}})])
    cache.update_pod(anti)
    enc.build_batch(asks)
    assert enc.last_encode_rows_reencoded == len(asks)   # full re-derive
