"""Observability layer: registry exposition correctness (validated line by
line with the mini Prometheus parser), per-pod latency spans, labelled
unschedulable accounting, and the Chrome-trace export round trip under the
pipelined cycle.
"""
import json
import math
import urllib.request

import pytest

from yunikorn_tpu.obs.metrics import (
    COUNT_BUCKETS,
    LATENCY_BUCKETS_S,
    MetricsRegistry,
)
from yunikorn_tpu.obs.promtext import (
    ParseError,
    parse_exposition,
    validate_exposition,
)

from tests.test_pipeline import NullCallback, asks_of, make_core  # noqa: F401
from yunikorn_tpu.client.synthetic import make_sleep_pods
from yunikorn_tpu.common.si import AllocationRequest


# --------------------------------------------------------------- registry
def test_registry_exposition_validates():
    r = MetricsRegistry()
    r.counter("reqs_total", "requests").inc(5)
    lab = r.counter("errs_total", "errors", labelnames=("kind",))
    lab.inc(2, kind="io")
    lab.inc(1, kind="weird\"quote\\slash\nnewline")
    r.gauge("depth", "queue depth").set(3.5)
    h = r.histogram("lat_seconds", "latency", buckets=LATENCY_BUCKETS_S)
    h.observe_batch([0.001, 0.3, 120.0])
    hl = r.histogram("batch_pods", "batch", labelnames=("stage",),
                     buckets=COUNT_BUCKETS)
    hl.observe(7, stage="solve")
    text = r.expose()
    assert validate_exposition(text, required=(
        "yunikorn_reqs_total", "yunikorn_errs_total", "yunikorn_depth",
        "yunikorn_lat_seconds", "yunikorn_batch_pods")) == []
    fams = parse_exposition(text)
    # TYPE correctness comes from declaration, not name heuristics
    assert fams["yunikorn_reqs_total"].kind == "counter"
    assert fams["yunikorn_depth"].kind == "gauge"
    assert fams["yunikorn_lat_seconds"].kind == "histogram"
    # label escaping round-trips bytes-exact
    kinds = {s.labels["kind"] for s in fams["yunikorn_errs_total"].samples}
    assert "weird\"quote\\slash\nnewline" in kinds
    # histogram series: cumulative buckets, +Inf == _count, sum matches
    e2e = fams["yunikorn_lat_seconds"]
    buckets = {s.labels["le"]: s.value for s in e2e.samples
               if s.name.endswith("_bucket")}
    assert buckets["+Inf"] == 3
    assert buckets["0.005"] == 1          # 0.001 lands in the first bucket
    assert buckets["60"] == 2             # 120 s only in +Inf
    count = next(s.value for s in e2e.samples if s.name.endswith("_count"))
    total = next(s.value for s in e2e.samples if s.name.endswith("_sum"))
    assert count == 3 and math.isclose(total, 120.301)


def test_registry_rejects_redeclaration_and_bad_labels():
    r = MetricsRegistry()
    r.counter("a_total", labelnames=("x",))
    with pytest.raises(ValueError):
        r.gauge("a_total")                     # kind change
    with pytest.raises(ValueError):
        r.counter("a_total", labelnames=())    # label-set change
    with pytest.raises(ValueError):
        r.counter("a_total").inc(1, y="nope")  # undeclared label
    with pytest.raises(ValueError):
        r.counter("bad name")                  # invalid metric name
    with pytest.raises(ValueError):
        r.counter("a_total").inc(-1, x="v")    # counters never decrease


def test_parser_flags_unregistered_emission_and_broken_histograms():
    # sample without a preceding # TYPE — the "unregistered emission" case
    with pytest.raises(ParseError):
        parse_exposition("yunikorn_rogue_metric 1\n")
    # non-monotone bucket series must fail validation
    bad = (
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 5\n'
        'h_bucket{le="2"} 3\n'
        'h_bucket{le="+Inf"} 5\n'
        "h_sum 4\n"
        "h_count 5\n")
    assert any("not monotone" in e for e in validate_exposition(bad))
    # +Inf bucket disagreeing with _count
    bad2 = (
        "# TYPE h histogram\n"
        'h_bucket{le="+Inf"} 4\n'
        "h_sum 4\n"
        "h_count 5\n")
    assert any("+Inf" in e for e in validate_exposition(bad2))


# ------------------------------------------------- core spans + reasons
def test_pod_spans_and_unschedulable_reasons():
    """submit→commit spans land in the stage histogram; the shim bind
    upcall (observe_pod_bound) closes the e2e histogram; an ask no node can
    hold counts as unschedulable_total{reason="capacity"}."""
    cache, core, _ = make_core(n_nodes=8)
    pods = make_sleep_pods(16, "app", queue="root.q", name_prefix="sp")
    giant = make_sleep_pods(1, "app", queue="root.q", name_prefix="sp-giant",
                            cpu_milli=10**9)
    core.update_allocation(AllocationRequest(asks=asks_of(pods + giant)))
    core.solver.pipeline = False
    core.schedule_once()
    count, total, _ = core._m_pod_stage.child_state(stage="schedule")
    assert count == 16 and total >= 0
    assert core._m_unschedulable.value(reason="capacity") >= 1
    # the shim's bind path reports back per pod; e2e closes then
    for p in pods:
        core.observe_pod_bound(p.uid)
    count, _, _ = core._m_pod_e2e.child_state()
    assert count == 16
    bind_count, _, _ = core._m_pod_stage.child_state(stage="bind")
    assert bind_count == 16
    # spans are popped at bind: a second report is a no-op
    core.observe_pod_bound(pods[0].uid)
    assert core._m_pod_e2e.child_state()[0] == 16


def test_metrics_snapshot_is_detached():
    """Satellite: metrics_snapshot deep-copies last_cycle under the lock —
    mutating the snapshot (or a later cycle) can't race a serializer."""
    cache, core, _ = make_core(n_nodes=8)
    pods = make_sleep_pods(4, "app", queue="root.q", name_prefix="ms")
    core.update_allocation(AllocationRequest(asks=asks_of(pods)))
    core.solver.pipeline = False
    core.schedule_once()
    snap = core.metrics_snapshot()
    entry = snap["last_cycle"]["default"]
    entry["pods"] = -999
    snap["last_cycle"]["bogus"] = {}
    fresh = core.metrics_snapshot()
    assert fresh["last_cycle"]["default"]["pods"] == 4
    assert "bogus" not in fresh["last_cycle"]
    # legacy read surface is the same snapshot
    assert core.metrics["allocation_attempt_allocated"] == 4


def test_exposition_full_surface_under_pipeline():
    """Every line the live core exposes must validate — TYPE correctness,
    bucket monotonicity, label escaping — including the per-partition
    cycle_* gauges and the pipeline gauges."""
    cache, core, _ = make_core(n_nodes=16)
    pods = make_sleep_pods(32, "app", queue="root.q", name_prefix="ex")
    core.update_allocation(AllocationRequest(asks=asks_of(pods)))
    core._pipeline_tick()
    core._pipeline_tick()
    text = core.obs.expose()
    assert validate_exposition(text, required=(
        "yunikorn_allocation_attempt_allocated",
        "yunikorn_solve_count",
        "yunikorn_pod_stage_latency_seconds",
        "yunikorn_cycle_stage_ms",
        "yunikorn_pipeline_overlap_ratio",
        "yunikorn_solve_batch_pods",
    )) == []
    fams = parse_exposition(text)
    cyc = fams["yunikorn_cycle_total_ms"]
    assert any(s.labels.get("partition") == "default" for s in cyc.samples)


# ------------------------------------------------------------- trace export
def _cycles_of(events):
    by_cycle = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        by_cycle.setdefault(e["args"]["cycle"], {})[e["name"]] = e
    return by_cycle


def test_chrome_trace_round_trip_pipelined():
    """Spans nest and cycle ids stay consistent under the pipelined path:
    gate→encode→dispatch precede solve; solve precedes materialize→commit;
    and the JSON is Perfetto-shaped (traceEvents, complete events with
    microsecond ts/dur, named lanes)."""
    cache, core, _ = make_core(n_nodes=16)
    for i, prefix in enumerate(("t1", "t2")):
        pods = make_sleep_pods(24, "app", queue="root.q", name_prefix=prefix)
        core.update_allocation(AllocationRequest(asks=asks_of(pods)))
        core._pipeline_tick()
    core._pipeline_tick()
    core._pipeline_tick()

    doc = json.loads(json.dumps(core.tracer.chrome_trace()))
    events = doc["traceEvents"]
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in events)
    xs = [e for e in events if e.get("ph") == "X"]
    assert xs, "no complete events exported"
    for e in xs:
        assert e["dur"] >= 0 and e["ts"] >= 0
        assert isinstance(e["args"]["cycle"], int)

    by_cycle = _cycles_of(events)
    finished = [c for c, st in by_cycle.items()
                if "commit" in st and "encode" in st]
    assert finished, by_cycle.keys()
    for c in finished:
        st = by_cycle[c]
        start = lambda n: st[n]["ts"]
        end = lambda n: st[n]["ts"] + st[n]["dur"]
        assert start("gate") <= start("encode") <= start("dispatch"), st
        assert end("dispatch") <= start("solve") + 1e-3
        assert end("solve") <= start("materialize") + 1e-3
        assert start("materialize") <= start("commit")
    # the overlap itself: cycle 2's encode starts before cycle 1 materializes
    if 1 in by_cycle and 2 in by_cycle and "materialize" in by_cycle[1]:
        assert (by_cycle[2]["encode"]["ts"]
                < by_cycle[1]["materialize"]["ts"])


def test_debug_traces_endpoint_and_events_filters():
    from yunikorn_tpu.common.events import get_recorder
    from yunikorn_tpu.webapp.rest import RestServer

    cache, core, _ = make_core(n_nodes=8)
    pods = make_sleep_pods(8, "app", queue="root.q", name_prefix="dt")
    core.update_allocation(AllocationRequest(asks=asks_of(pods)))
    core._pipeline_tick()
    core._pipeline_tick()
    rec = get_recorder()
    rec.eventf("Pod", "default/dt-a", "Warning", "ObsTestFailed", "boom")
    rec.eventf("Pod", "default/dt-b", "Normal", "ObsTestScheduled", "ok")
    rest = RestServer(core, None, port=0)
    port = rest.start()
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return json.loads(r.read())

        doc = get("/debug/traces")
        names = {e["name"] for e in doc["traceEvents"]}
        assert {"encode", "solve", "commit"} <= names
        ev = get("/ws/v1/events?reason=ObsTestFailed")
        assert [e["objectID"] for e in ev["EventRecords"]] == ["default/dt-a"]
        ev = get("/ws/v1/events?objectKey=default/dt-b")
        assert [e["reason"] for e in ev["EventRecords"]] == ["ObsTestScheduled"]
        # the two metrics surfaces render from one registry snapshot
        mjson = get("/ws/v1/metrics")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert validate_exposition(text) == []
        fams = parse_exposition(text)
        assert (fams["yunikorn_allocation_attempt_allocated"].samples[0].value
                == mjson["allocation_attempt_allocated"])
    finally:
        rest.stop()
