"""CvxCluster solver arm (round 19, solver.pack=cvx).

Pins the arm's safety contracts, mirroring the pack suite's structure:
  - every placement the full-fleet convex relaxation emits passes the exact
    greedy-side feasibility (host predicates + per-node capacity) — the
    rounding/repair path IS the greedy accept machinery;
  - the duel commits cvx only on a strictly better priority-guarded key
    (ties keep greedy), and a GARBAGE learned-dual warm start can only cost
    packed units — degrade to a duel loss, never a mis-commit;
  - sharded-mesh dispatch is placement-identical to the single-device solve;
  - the fused learned chunk pass (_learned_chunk_pass, follow-up (e)) is
    bit-identical to the two separate passes it replaced;
  - solver.policy=learned on a sharded mesh actually scores (follow-up (c):
    the mesh wrapper threads the params — no more silent skip);
  - the conftest durations-ledger guard flags overlong unmarked tests.
"""
import json
import os

import numpy as np
import pytest

import conftest as _root_conftest
from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.conf import schedulerconf as sc
from yunikorn_tpu.core.scheduler import CoreScheduler, SolverOptions
from yunikorn_tpu.ops import cvx_solve as cvx_mod
from yunikorn_tpu.ops import pack_solve as pack_mod
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.ops.host_predicates import pod_fits_node
from yunikorn_tpu.policy import net as pnet
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

from tests.test_pack_solve import _CB, build_trace


# ---------------------------------------------------------------- unit: gates
def test_cvx_shape_gate_is_deterministic_in_shape():
    budget = cvx_mod._CVX_CELL_BUDGET
    assert cvx_mod.cvx_shape_supported(4096, 8192)
    assert cvx_mod.cvx_shape_supported(budget // 128, 128)
    assert not cvx_mod.cvx_shape_supported(budget // 128 + 1, 128)
    assert not cvx_mod.cvx_shape_supported(0, 128)
    assert not cvx_mod.cvx_shape_supported(128, 0)


def test_project_rows_capped_simplex_properties():
    """The bisection projection lands inside {p >= 0, sum <= 1, p[~ok]=0}
    and leaves already-feasible rows (sum <= 1) untouched."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 24).astype(np.float32) * 2.0)
    ok = jnp.asarray(rng.rand(16, 24) < 0.7)
    p = np.asarray(cvx_mod._project_rows(x, ok.astype(jnp.float32)))
    assert (p >= 0.0).all()
    # τ is bisected to 2^-12 of the mass scale; the row sum can overshoot
    # 1 by O(M · 2^-PROJ_BISECT) — the capacity projection downstream is
    # what enforces the hard resource box, not the simplex cap
    assert (p.sum(axis=1) <= 1.0 + 24 * 2.0 ** -cvx_mod._PROJ_BISECT).all()
    assert (p[~np.asarray(ok)] == 0.0).all()
    feas = jnp.asarray(np.clip(rng.rand(8, 24).astype(np.float32) * 0.04,
                               0, None))
    kept = np.asarray(cvx_mod._project_rows(feas, jnp.ones((8, 24))))
    np.testing.assert_allclose(kept, np.asarray(feas), atol=1e-6)


def test_cvx_unsupported_batches_raise():
    """Host-port batches are outside the full-fleet model: explicit
    CvxUnsupported before any device work, never a silently wrong plan."""
    cache = SchedulerCache()
    for i in range(4):
        cache.update_node(make_node(f"n{i}", cpu_milli=4000,
                                    memory=8 * 2**30))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    port_pod = make_pod("pp", cpu_milli=100, memory=2**20)
    port_pod.spec.containers[0].ports = [{"hostPort": 9000,
                                          "protocol": "TCP"}]
    batch = enc.build_batch([AllocationAsk(
        port_pod.uid, "app", get_pod_resource(port_pod), pod=port_pod)])
    with pytest.raises(cvx_mod.CvxUnsupported):
        cvx_mod.cvx_solve_batch(batch, enc.nodes)


# ------------------------------------------------------------------ unit: conf
def test_conf_solver_pack_parsing_and_decision_table():
    conf = sc.parse_config_map({"solver.pack": "cvx"})
    assert conf.solver_pack == "cvx"
    assert SolverOptions.from_conf(conf).pack == "cvx"
    assert SolverOptions.from_conf(
        sc.parse_config_map({"solver.pack": "pop"})).pack == "pop"
    assert SolverOptions.from_conf(sc.parse_config_map({})).pack == "auto"
    with pytest.raises(ValueError):
        sc.parse_config_map({"solver.pack": "simplex"})

    def core_for(policy, pack="auto"):
        c = SchedulerCache()
        return CoreScheduler(c, solver_options=SolverOptions(
            policy=policy, pack=pack))

    core = core_for("optimal", "cvx")
    assert core._cvx_on() and not core._pack_on()
    core = core_for("optimal", "auto")
    assert core._pack_on() and not core._cvx_on()
    core = core_for("all")
    assert core._pack_on() and core._cvx_on()
    core = core_for("greedy")
    assert not core._pack_on() and not core._cvx_on()


# ------------------------------------------------------- unit: duel strictness
def test_duel_commits_cvx_only_on_strict_win():
    """The N-way fold with a cvx challenger: ties keep the greedy incumbent,
    a strictly better key commits, the priority guard still vetoes a plan
    that starves a higher class for units."""
    req = np.full((4, 2), 10, np.int32)
    valid = np.ones(4, bool)
    g = np.array([0, 0, 1, -1], np.int32)
    tie = np.array([1, 1, 0, -1], np.int32)
    more = np.array([0, 0, 1, 1], np.int32)
    winner, _ = pack_mod.choose_plan_n([("greedy", g), ("cvx", tie)],
                                       req, valid)
    assert winner == "greedy"
    winner, _ = pack_mod.choose_plan_n([("greedy", g), ("cvx", more)],
                                       req, valid)
    assert winner == "cvx"
    prio = np.array([100, 0, 0, 0], np.int64)
    req_p = np.array([[1, 1], [50, 50], [50, 50], [50, 50]], np.int32)
    g_p = np.array([0, 0, -1, -1], np.int32)       # places the prio-100 ask
    cvx_p = np.array([-1, 0, 1, 2], np.int32)      # more units, starves it
    winner, _ = pack_mod.choose_plan_n([("greedy", g_p), ("cvx", cvx_p)],
                                       req_p, valid, priorities=prio)
    assert winner == "greedy"


# ------------------------------------------- unit: fused learned pass (sat. e)
@pytest.mark.parametrize("policy", ["binpacking", "align"])
@pytest.mark.parametrize("with_topo", [False, True])
def test_fused_learned_pass_bit_identical_to_separate_passes(policy,
                                                             with_topo):
    """Follow-up (e) regression pin: the fused _learned_chunk_pass must be
    bit-identical to the two passes it replaced — its argmax tail to
    _best_nodes_chunked with the learned score augmentation, and its gated
    proposal to the argmax-free variant (the two lax.cond branches must
    agree exactly or round parity would change placements)."""
    import jax
    import jax.numpy as jnp

    from yunikorn_tpu.ops.assign import _best_nodes_chunked, \
        _learned_chunk_pass

    rng = np.random.RandomState(7)
    N, M, R, E, G, chunk = 64, 32, 2, 8, 16, 32
    req = jnp.asarray(rng.randint(0, 6, (N, R)).astype(np.int32))
    gid = jnp.asarray((np.arange(N) % G).astype(np.int32))
    gfeas = jnp.asarray(rng.rand(G, M) < 0.8)
    gsoft = jnp.asarray(rng.randn(G, M).astype(np.float32) * 0.1)
    free = jnp.asarray(rng.randint(0, 12, (M, R)).astype(np.int32))
    cap = jnp.asarray(np.full((M, R), 12, np.int32))
    base = jnp.asarray(rng.rand(M).astype(np.float32))
    pod_emb = jnp.asarray(rng.randn(N, E).astype(np.float32))
    node_emb = jnp.asarray(rng.randn(M, E).astype(np.float32))
    active = jnp.asarray(rng.rand(N) < 0.9)
    key = jax.random.PRNGKey(3)
    node_dom = (jnp.asarray((np.arange(M) % 4).astype(np.int32))
                if with_topo else None)
    pref_pod = (jnp.asarray(rng.randint(-1, 4, N).astype(np.int32))
                if with_topo else None)

    prop_t, best_t, feas_t = _learned_chunk_pass(
        pod_emb, node_emb, gid, gfeas, gsoft, free, cap, base, req, active,
        jnp.float32(0.3), key, chunk, policy, 0, node_dom=node_dom,
        pref_pod=pref_pod, argmax=True)
    prop_f, _, _ = _learned_chunk_pass(
        pod_emb, node_emb, gid, gfeas, gsoft, free, cap, base, req, active,
        jnp.float32(0.3), key, chunk, policy, 0, node_dom=node_dom,
        pref_pod=pref_pod, argmax=False)
    assert np.array_equal(np.asarray(prop_t), np.asarray(prop_f))

    ref_best, ref_feas = _best_nodes_chunked(
        req, gid, gfeas, gsoft, free, cap, base, chunk, policy, 0,
        node_dom=node_dom, pref_pod=pref_pod,
        learned_emb=(pod_emb, node_emb))
    assert np.array_equal(np.asarray(best_t), np.asarray(ref_best))
    assert np.array_equal(np.asarray(feas_t), np.asarray(ref_feas))

    # untrained-is-inert: a zero pod tower can never fire the gate
    prop_z, _, _ = _learned_chunk_pass(
        jnp.zeros((N, E)), node_emb, gid, gfeas, gsoft, free, cap, base,
        req, active, jnp.float32(0.3), key, chunk, policy, 0, argmax=False)
    assert (np.asarray(prop_z) == M).all()


# ------------------------------------------------ unit: bench acceptance rule
def test_cvx_bench_quality_rule_matches_issue_acceptance():
    """The gang acceptance (--beat greedy,learned) tolerates a pack-arm
    units tie and an unbounded latency ratio; the smoke default does not."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "cvx_bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "cvx_bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    # shape of a real recorded gang line: cvx wins, ties pack on units,
    # dense solve well past the smoke latency bound
    gang = {"pods": 4096, "nodes": 4096, "winner": "cvx", "cvx_wins": True,
            "cvx_solve_ms": 27772.0, "latency_ratio": 9.23,
            "greedy_units": 5872709, "pack_units": 11383808,
            "cvx_units": 11383808, "learned_units": 5872709}
    assert bench.quality_failures(gang, ["greedy", "learned"], 0) == []
    strict = bench.quality_failures(gang, ["greedy", "pack", "learned"], 3.0)
    assert len(strict) == 2 and "pack" in strict[0] and "9.23x" in strict[1]
    # smoke record: strict win over every arm inside the bound
    smoke = dict(gang, latency_ratio=0.91, cvx_units=11383809)
    assert bench.quality_failures(
        smoke, ["greedy", "pack", "learned"], 3.0) == []
    # a duel loss fails regardless of the beat list
    lost = dict(smoke, cvx_wins=False, winner="optimal")
    assert bench.quality_failures(lost, ["greedy"], 0) != []


# ----------------------------------------------- unit: durations ledger guard
def test_durations_ledger_guard_flags_overlong_unmarked():
    ledger = {"tests/a.py::t_fast": 0.3,
              "tests/a.py::t_slow_marked": 9.0,
              "tests/a.py::t_slow_unmarked": 4.2}
    entries = [("tests/a.py::t_fast", False),
               ("tests/a.py::t_slow_marked", True),
               ("tests/a.py::t_slow_unmarked", False),
               ("tests/a.py::t_unknown", False)]   # no ledger entry: pass
    bad = _root_conftest.overlong_unmarked(entries, ledger)
    assert bad == [("tests/a.py::t_slow_unmarked", 4.2)]
    assert _root_conftest.overlong_unmarked(entries, {}) == []


def test_durations_ledger_fails_collection(tmp_path, monkeypatch):
    """With a ledger present, collection must abort on an unmarked
    offender — exercised through pytest_collection_modifyitems with stub
    items (running a child pytest would cost seconds)."""
    ledger_file = tmp_path / ".durations.json"
    ledger_file.write_text(json.dumps({"tests/x.py::t": 5.0}))
    monkeypatch.setattr(_root_conftest, "DURATIONS_LEDGER",
                        str(ledger_file))

    class _Item:
        nodeid = "tests/x.py::t"

        def get_closest_marker(self, name):
            return None

    with pytest.raises(pytest.UsageError):
        _root_conftest.pytest_collection_modifyitems(None, [_Item()])


# ----------------------------------------------------- feasibility (device)
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(4))
def test_cvx_placements_pass_greedy_side_feasibility(seed):
    """Every placement the cvx plan emits must satisfy the exact host
    predicates and per-node capacity on randomized fragmented traces —
    the rounding/repair path is greedy feasibility by construction."""
    cache, enc, nodes, pods, asks, batch = build_trace(seed)
    result = cvx_mod.cvx_solve_batch(batch, enc.nodes, seed=seed)
    assert bool(np.asarray(result.feasible))
    assigned = np.asarray(result.assigned)[: batch.num_pods]
    assert int(np.asarray(result.free_after).min()) >= 0

    by_name = {n.name: n for n in nodes}
    placed_on = {}
    for i, pod in enumerate(pods):
        idx = int(assigned[i])
        if idx >= 0:
            placed_on.setdefault(enc.nodes.name_of(idx), []).append(pod)
    for name, placed in placed_on.items():
        node = by_name[name]
        free = cache.get_node(name).available()
        for k, pod in enumerate(placed):
            others = placed[:k] + placed[k + 1:]
            err = pod_fits_node(pod, node, free, others)
            assert err in (None, "insufficient resources"), (
                seed, name, pod.name, err)
        for res in ("cpu", "memory"):
            total = sum(get_pod_resource(p).get(res) for p in placed)
            assert total <= free.get(res), (seed, name, res, total)


@pytest.mark.slow
def test_cvx_seeded_determinism():
    _, enc, _, _, _, batch = build_trace(2)
    a = np.asarray(cvx_mod.cvx_solve_batch(batch, enc.nodes,
                                           seed=123).assigned)
    b = np.asarray(cvx_mod.cvx_solve_batch(batch, enc.nodes,
                                           seed=123).assigned)
    assert np.array_equal(a, b)


@pytest.mark.slow
def test_cvx_garbage_dual_degrades_to_loss_never_miscommit():
    """A garbage learned-dual warm start may cost packed units — the duel
    then keeps the incumbent — but the emitted plan must STILL be feasible
    and a commit still requires a strictly better key."""
    import jax

    _, enc, _, _, _, batch = build_trace(1)
    n = batch.num_pods
    ga = np.asarray(solve_batch(batch, enc.nodes).assigned)[:n]
    garbage = jax.tree_util.tree_map(
        lambda a: a + 7.0 * jax.random.normal(
            jax.random.PRNGKey(13), np.shape(a)).astype(np.float32),
        pnet.init_params(0))
    res = cvx_mod.cvx_solve_batch(batch, enc.nodes, seed=5, learned=garbage)
    assert res.learned_dual
    assert bool(np.asarray(res.feasible))          # never infeasible
    assert int(np.asarray(res.free_after).min()) >= 0
    ca = np.asarray(res.assigned)[:n]
    winner, stats = pack_mod.choose_plan_n(
        [("greedy", ga), ("cvx", ca)], batch.req.astype(np.int32),
        batch.valid)
    if winner == "cvx":                            # commit ⇒ strictly better
        assert stats["cvx"]["units"] > stats["greedy"]["units"] or \
            stats["cvx"]["placed"] > stats["greedy"]["placed"]

    # zero params ⇒ dual warm start is exactly the cold start (inert)
    cold = np.asarray(cvx_mod.cvx_solve_batch(batch, enc.nodes,
                                              seed=5).assigned)
    warm0 = np.asarray(cvx_mod.cvx_solve_batch(
        batch, enc.nodes, seed=5, learned=pnet.init_params(0)).assigned)
    assert np.array_equal(cold, warm0)


@pytest.mark.slow
def test_cvx_sharded_parity_with_single_device():
    """parallel.mesh.cvx_solve_sharded over the virtual 8-device mesh must
    reproduce the single-device plan bit-for-bit (same seed, same trace)."""
    from yunikorn_tpu.parallel import mesh as mesh_mod

    _, enc, _, _, _, batch = build_trace(4)
    n = batch.num_pods
    single = cvx_mod.cvx_solve_batch(batch, enc.nodes, seed=9)
    sharded = mesh_mod.cvx_solve_sharded(batch, enc.nodes,
                                         mesh_mod.make_mesh(), seed=9)
    assert bool(np.asarray(sharded.feasible))
    assert np.array_equal(np.asarray(single.assigned)[:n],
                          np.asarray(sharded.assigned)[:n])
    assert np.array_equal(np.asarray(single.free_after),
                          np.asarray(sharded.free_after))


# ------------------------------------------------------------------ core e2e
def _make_core(**solver_kw):
    from yunikorn_tpu.common.si import RegisterResourceManagerRequest

    cache = SchedulerCache()
    core = CoreScheduler(cache, solver_options=SolverOptions(**solver_kw))
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="t", policy_group="queues",
                                       config=""), _CB())
    return cache, core


def _run_trace(core, cache, n_nodes=32, waves=2, per_wave=60, cpu=400):
    from tests.test_pack_solve import run_core_trace

    return run_core_trace(core, cache, n_nodes=n_nodes, waves=waves,
                          per_wave=per_wave, cpu=cpu)


@pytest.mark.slow
def test_core_cvx_arm_commits_valid_plan_and_metrics():
    """solver.pack=cvx through the full core cycle: every committed
    allocation lands within capacity, the duel ran with the cvx arm
    (won or fell back — never silently absent), and the cycle entry
    carries the cvx observability keys."""
    cache, core = _make_core(policy="optimal", pack="cvx")
    placements = _run_trace(core, cache)
    assert len(placements) == 120
    per_node = {}
    for _, node in placements.items():
        per_node[node] = per_node.get(node, 0) + 400
    for node, used in per_node.items():
        info = cache.get_node(node)
        assert info is not None
        assert used <= info.allocatable.get("cpu")
    c = core.obs.get("cvx_plans_total")
    assert c.value(outcome="won") + c.value(outcome="fell_back") >= 1
    assert c.value(outcome="infeasible") == 0
    wins = core.obs.get("duel_wins_total")
    assert sum(wins.value(arm=a)
               for a in ("greedy", "cvx", "optimal", "learned")) >= 1
    entry = (core.metrics.get("last_cycle") or {}).get("default") or {}
    assert "cvx_util" in entry or "cvx_skip" in entry
    if "cvx_util" in entry:
        assert "cvx_solve_ms" in entry and "cvx_iters" in entry


@pytest.mark.slow
def test_core_cvx_fault_falls_back_to_greedy_placements():
    """A faulted cvx path must leave the cycle exactly greedy: placements
    identical to a policy=greedy run, outcome counted, loop never wedged."""
    cache_g, core_g = _make_core(policy="greedy")
    want = _run_trace(core_g, cache_g)
    cache_c, core_c = _make_core(policy="optimal", pack="cvx")
    core_c.supervisor.faults.fail("cvx", times=8, tier="device")
    got = _run_trace(core_c, cache_c)
    assert got == want
    c = core_c.obs.get("cvx_plans_total")
    assert c.value(outcome="failed") + c.value(outcome="skipped") >= 1


@pytest.mark.slow
def test_core_learned_arm_scores_on_sharded_mesh(tmp_path):
    """Follow-up (c): solver.policy=learned with node-dim sharding enabled
    must actually run the learned arm (the mesh wrapper threads the params)
    — placements stay bit-identical to greedy under an untrained checkpoint,
    and the duel records the learned arm instead of a 'mesh' skip."""
    prefix = str(tmp_path / "ck")
    pnet.save_checkpoint(prefix, pnet.init_params(0), epoch=1)
    cache_l, core_l = _make_core(policy="learned", policy_checkpoint=prefix,
                                 shard=True)
    placements_l = _run_trace(core_l, cache_l)
    cache_g, core_g = _make_core(policy="greedy", shard=True)
    placements_g = _run_trace(core_g, cache_g)
    assert placements_l == placements_g
    assert len(placements_l) == 120
    assert core_l._mesh is not None            # sharding actually resolved
    duels = core_l.obs.get("policy_duels_total")
    assert duels.value(policy="learned", outcome="lost") \
        + duels.value(policy="learned", outcome="won") == 2
    entry = core_l.metrics["last_cycle"]["default"]
    assert entry.get("policy_skip") != "mesh"
    assert entry.get("learned_util") == 1.0
