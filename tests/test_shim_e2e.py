"""End-to-end shim tests over MockScheduler: real core + real shim + fake
cluster, full submit→bind cycles (reference scheduler_test.go /
scheduler_mock_test.go pattern).
"""
import time

import pytest

from yunikorn_tpu.cache import application as app_mod
from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.shim.mock_scheduler import MockScheduler

QUEUES_YAML = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: default
          - name: tiny
            resources:
              max: {vcore: 1, memory: 1Gi}
"""


@pytest.fixture
def sched():
    ms = MockScheduler()
    ms.init(QUEUES_YAML)
    ms.start()
    yield ms
    ms.stop()


def yk_pod(name, app_id="app-1", queue="root.default", cpu=500, mem=2**28, **kw):
    return make_pod(
        name,
        cpu_milli=cpu,
        memory=mem,
        labels={constants.LABEL_APPLICATION_ID: app_id,
                constants.LABEL_QUEUE_NAME: queue},
        scheduler_name=constants.SCHEDULER_NAME,
        **kw,
    )


def test_submit_to_bind_cycle(sched):
    sched.add_node(make_node("node-1", cpu_milli=4000))
    pod = sched.add_pod(yk_pod("pod-1"))
    sched.wait_for_task_state("app-1", pod.uid, task_mod.BOUND)
    sched.wait_for_app_state("app-1", app_mod.RUNNING)
    assert sched.get_pod_assignment(pod) == "node-1"
    assert sched.get_active_node_count_in_core() == 1
    assert sched.bind_stats().success_count == 1


def test_many_pods_many_nodes(sched):
    sched.add_nodes([make_node(f"node-{i}", cpu_milli=8000) for i in range(4)])
    pods = [sched.add_pod(yk_pod(f"pod-{i}", cpu=1000)) for i in range(20)]
    sched.wait_for_bound_count(20)
    for p in pods:
        assert sched.get_pod_assignment(p)
    # per-node capacity respected: max 8 pods of 1000m on an 8000m node
    counts = {}
    for p in pods:
        n = sched.get_pod_assignment(p)
        counts[n] = counts.get(n, 0) + 1
    assert max(counts.values()) <= 8


def test_pod_completion_releases_capacity(sched):
    sched.add_node(make_node("node-1", cpu_milli=1000))
    p1 = sched.add_pod(yk_pod("pod-1", cpu=1000))
    sched.wait_for_task_state("app-1", p1.uid, task_mod.BOUND)
    p2 = sched.add_pod(yk_pod("pod-2", cpu=1000))
    time.sleep(0.3)
    assert sched.get_pod_assignment(p2) == ""  # no capacity yet
    sched.succeed_pod(p1)
    sched.wait_for_task_state("app-1", p2.uid, task_mod.BOUND)
    assert sched.get_pod_assignment(p2) == "node-1"


def test_queue_quota_enforced_e2e(sched):
    sched.add_node(make_node("node-1", cpu_milli=16000))
    pods = [sched.add_pod(yk_pod(f"pod-{i}", app_id="tiny-app", queue="root.tiny",
                                 cpu=500, mem=2**28)) for i in range(4)]
    sched.wait_for_bound_count(2)  # 1 vcore max → two 500m pods
    time.sleep(0.3)
    assert sched.bind_stats().success_count == 2


def test_app_rejected_for_parent_queue(sched):
    sched.add_node(make_node("node-1"))
    pod = sched.add_pod(yk_pod("pod-1", app_id="bad-app", queue="root"))
    sched.wait_for_app_state("bad-app", app_mod.FAILED)
    task = sched.context.get_application("bad-app").get_task(pod.uid)
    assert task.state == task_mod.FAILED


def test_unschedulable_pod_gets_condition(sched):
    sched.add_node(make_node("node-1", cpu_milli=1000))
    pod = sched.add_pod(yk_pod("pod-1", cpu=4000))  # never fits
    deadline = time.time() + 5
    cur = None
    while time.time() < deadline:
        cur = sched.cluster.get_pod(pod.uid)
        if any(c.type == "PodScheduled" and c.status == "False" for c in cur.status.conditions):
            break
        time.sleep(0.05)
    conds = [c for c in cur.status.conditions if c.type == "PodScheduled"]
    assert conds and conds[0].reason == "Unschedulable"


def test_node_selector_respected_e2e(sched):
    sched.add_nodes([
        make_node("gpu-node", labels={"accel": "tpu"}),
        make_node("cpu-node"),
    ])
    pod = yk_pod("pod-1")
    pod.spec.node_selector = {"accel": "tpu"}
    sched.add_pod(pod)
    sched.wait_for_task_state("app-1", pod.uid, task_mod.BOUND)
    assert sched.get_pod_assignment(pod) == "gpu-node"


def test_foreign_pod_occupies_capacity(sched):
    sched.add_node(make_node("node-1", cpu_milli=2000))
    # a foreign pod (no app id, not our scheduler) already running on the node
    foreign = make_pod("foreign-1", cpu_milli=1500, node_name="node-1", phase="Running")
    sched.add_pod(foreign)
    time.sleep(0.2)
    ours = sched.add_pod(yk_pod("pod-1", cpu=1000))
    time.sleep(0.5)
    assert sched.get_pod_assignment(ours) == ""  # 1500 of 2000 occupied
    # foreign pod finishes → capacity frees
    sched.cluster.succeed_pod(foreign.uid)
    sched.wait_for_task_state("app-1", ours.uid, task_mod.BOUND)


def test_pod_deleted_releases(sched):
    sched.add_node(make_node("node-1", cpu_milli=1000))
    p1 = sched.add_pod(yk_pod("pod-1", cpu=1000))
    sched.wait_for_task_state("app-1", p1.uid, task_mod.BOUND)
    sched.delete_pod(p1)
    p2 = sched.add_pod(yk_pod("pod-2", cpu=1000))
    sched.wait_for_task_state("app-1", p2.uid, task_mod.BOUND)


def test_two_apps_two_queues(sched):
    sched.add_nodes([make_node(f"n{i}", cpu_milli=4000) for i in range(2)])
    a = sched.add_pod(yk_pod("a-pod", app_id="app-a", queue="root.default"))
    b = sched.add_pod(yk_pod("b-pod", app_id="app-b", queue="root.dynamic"))
    sched.wait_for_task_state("app-a", a.uid, task_mod.BOUND)
    sched.wait_for_task_state("app-b", b.uid, task_mod.BOUND)
    dao = sched.core.get_partition_dao()
    assert dao["partition"]["applications"]["app-a"]["queue"] == "root.default"
    assert dao["partition"]["applications"]["app-b"]["queue"] == "root.dynamic"


# ---------------------------------------------------------------------------
# Recovery
# ---------------------------------------------------------------------------

def test_recovery_restores_bound_pods():
    ms = MockScheduler()
    ms.init(QUEUES_YAML)
    # cluster state exists BEFORE the scheduler starts
    ms.cluster.add_node(make_node("node-1", cpu_milli=4000))
    bound = yk_pod("already-bound", cpu=1000)
    bound.spec.node_name = "node-1"
    bound.status.phase = "Running"
    ms.cluster.add_pod(bound)
    pending = yk_pod("pending-pod", cpu=1000)
    ms.cluster.add_pod(pending)
    ms.start()
    try:
        # recovered pod fast-forwarded to Bound without a new bind
        ms.wait_for_task_state("app-1", bound.uid, task_mod.BOUND)
        # pending pod gets scheduled normally after recovery
        ms.wait_for_task_state("app-1", pending.uid, task_mod.BOUND)
        # recovered allocation occupies capacity in the core's accounting
        leaf = ms.core.queues.resolve("root.default", create=False)
        assert leaf.allocated.get("cpu") == 2000
        # only ONE bind happened (the pending pod); the recovered pod was not rebound
        assert ms.bind_stats().success_count == 1
    finally:
        ms.stop()


def test_recovery_orphaned_pod_adopted():
    ms = MockScheduler()
    ms.init(QUEUES_YAML)
    # pod references a node that doesn't exist yet
    orphan = yk_pod("orphan", cpu=500)
    orphan.spec.node_name = "late-node"
    orphan.status.phase = "Running"
    ms.cluster.add_pod(orphan)
    ms.start()
    try:
        assert ms.context.schedulers_cache.is_pod_orphaned(orphan.uid)
        ms.add_node(make_node("late-node"))
        deadline = time.time() + 5
        while time.time() < deadline and ms.context.schedulers_cache.is_pod_orphaned(orphan.uid):
            time.sleep(0.05)
        assert not ms.context.schedulers_cache.is_pod_orphaned(orphan.uid)
        info = ms.context.schedulers_cache.get_node("late-node")
        assert info.requested.get("cpu") == 500
    finally:
        ms.stop()


def test_config_hot_reload_updates_quota():
    ms = MockScheduler()
    ms.init(QUEUES_YAML)
    ms.start()
    try:
        ms.add_node(make_node("node-1", cpu_milli=16000))
        new_yaml = QUEUES_YAML.replace("vcore: 1,", "vcore: 3,")
        ms.update_config(new_yaml)
        deadline = time.time() + 5
        ok = False
        while time.time() < deadline:
            leaf = ms.core.queues.resolve("root.tiny", create=False)
            if leaf is not None and leaf.config.max_resource and \
                    leaf.config.max_resource.get("cpu") == 3000:
                ok = True
                break
            time.sleep(0.05)
        assert ok, "queue config did not hot-reload"
    finally:
        ms.stop()


def test_in_place_pod_resize_updates_capacity(sched):
    """pod_resource_scaling e2e analog: an in-place resize (KEP-1287) changes
    the pod's effective request via container statuses; the cache re-accounts
    the node and subsequent scheduling sees the new free capacity."""
    sched.add_node(make_node("node-1", cpu_milli=4000))
    p1 = sched.add_pod(yk_pod("resizable", cpu=1000))
    sched.wait_for_task_state("app-1", p1.uid, task_mod.BOUND)
    info = sched.context.schedulers_cache.get_node("node-1")
    assert info.requested.get("cpu") == 1000
    # resize up to 3000m: status-level allocated resources win over spec
    resized = p1.deepcopy()
    resized.status.container_statuses = [
        {"name": "c0", "resources": {"requests": {"cpu": "3", "memory": str(2**28)}}}]
    sched.cluster.update_pod(resized)
    deadline = time.time() + 5
    while time.time() < deadline:
        info = sched.context.schedulers_cache.get_node("node-1")
        if info.requested.get("cpu") == 3000:
            break
        time.sleep(0.05)
    assert info.requested.get("cpu") == 3000
    # only 1000m free now: a 2000m pod must not fit
    p2 = sched.add_pod(yk_pod("big", cpu=2000))
    time.sleep(0.4)
    assert sched.get_pod_assignment(p2) == ""
    p3 = sched.add_pod(yk_pod("small", cpu=900))
    sched.wait_for_task_state("app-1", p3.uid, task_mod.BOUND)


def test_restart_with_changed_config():
    """restart_changed_config e2e analog: the scheduler restarts against the
    same cluster with a DIFFERENT queues.yaml; recovered state must respect
    the new configuration."""
    ms = MockScheduler()
    ms.init(QUEUES_YAML)
    ms.start()
    ms.add_node(make_node("node-1", cpu_milli=16000))
    pods = [ms.add_pod(yk_pod(f"pod-{i}", cpu=1000)) for i in range(2)]
    for p in pods:
        ms.wait_for_task_state("app-1", p.uid, task_mod.BOUND)
    cluster = ms.cluster  # the "cluster" survives the scheduler restart
    ms.core.stop()
    ms.shim.stop()

    # restart with root.default now capped at 3 vcore
    new_yaml = QUEUES_YAML.replace(
        "          - name: default\n",
        "          - name: default\n            resources:\n              max: {vcore: 3}\n",
    )
    from yunikorn_tpu.cache.context import Context
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.conf.schedulerconf import get_holder, reset_for_tests
    from yunikorn_tpu.core.scheduler import CoreScheduler
    from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
    from yunikorn_tpu.shim.scheduler import KubernetesShim

    reset_for_tests()
    get_holder().update_config_maps(
        [{"service.schedulingInterval": "0.05", "queues.yaml": new_yaml}], initial=True)
    dispatch_mod.reset_dispatcher()
    cache2 = SchedulerCache()
    core2 = CoreScheduler(cache2, interval=0.02)
    ctx2 = Context(cluster, core2, cache=cache2)
    shim2 = KubernetesShim(cluster, core2, context=ctx2)
    core2.start()
    shim2.run()
    try:
        # recovered: both pods Bound again without rebinding; 2000m accounted
        deadline = time.time() + 10
        while time.time() < deadline:
            app = ctx2.get_application("app-1")
            if app is not None and all(
                    (t := app.get_task(p.uid)) is not None and t.state == task_mod.BOUND
                    for p in pods):
                break
            time.sleep(0.05)
        leaf = core2.queues.resolve("root.default", create=False)
        assert leaf.allocated.get("cpu") == 2000
        assert leaf.config.max_resource.get("cpu") == 3000  # new config applied
        # new quota enforced on top of recovered usage: only 1 more vcore fits
        extra = [cluster.add_pod(yk_pod(f"extra-{i}", cpu=1000)) for i in range(3)]
        deadline = time.time() + 5
        while time.time() < deadline and leaf.allocated.get("cpu") < 3000:
            time.sleep(0.05)
        time.sleep(0.3)
        assert leaf.allocated.get("cpu") == 3000  # capped by the NEW max
    finally:
        shim2.stop()
        core2.stop()


# ---------------------------------------------------------------------------
# Volumes (persistent_volume e2e analog)
# ---------------------------------------------------------------------------

def test_pod_with_pvc_binds_volume_then_pod(sched):
    from yunikorn_tpu.common.objects import ObjectMeta, PersistentVolumeClaim, Volume

    sched.add_node(make_node("node-1"))
    sched.cluster.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name="claim-1", namespace="default"),
        storage_class="standard"))
    pod = yk_pod("with-vol")
    pod.spec.volumes = [Volume(name="data", pvc_claim_name="claim-1")]
    sched.add_pod(pod)
    sched.wait_for_task_state("app-1", pod.uid, task_mod.BOUND)
    pvc = sched.cluster.get_pvc("default", "claim-1")
    assert pvc.bound and pvc.volume_name  # volume bound before the pod bind


def test_pod_with_missing_pvc_fails(sched):
    from yunikorn_tpu.common.objects import Volume

    sched.add_node(make_node("node-1"))
    pod = yk_pod("no-claim")
    pod.spec.volumes = [Volume(name="data", pvc_claim_name="ghost-claim")]
    sched.add_pod(pod)
    sched.wait_for_task_state("app-1", pod.uid, task_mod.FAILED)


def test_node_volume_attach_limit(sched):
    """NodeVolumeLimits analog: pods consume attach slots; a node with a low
    published limit rejects overflow."""
    from yunikorn_tpu.common.objects import ObjectMeta, PersistentVolumeClaim, Volume

    node = make_node("vol-node", cpu_milli=16000)
    node.status.allocatable["attachable-volumes-csi"] = 2
    sched.add_node(node)
    for i in range(3):
        sched.cluster.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name=f"c{i}", namespace="default")))
    pods = []
    for i in range(3):
        p = yk_pod(f"vp-{i}", cpu=100)
        p.spec.volumes = [Volume(name="d", pvc_claim_name=f"c{i}")]
        pods.append(sched.add_pod(p))
    sched.wait_for_bound_count(2)
    time.sleep(0.4)
    bound = [p for p in pods if sched.get_pod_assignment(p)]
    assert len(bound) == 2  # attach limit 2 caps the third


def test_app_completes_when_all_tasks_done(sched):
    """Core completes idle Running apps (Completing→Completed) and the shim
    garbage-collects them (reference app lifecycle end)."""
    sched.core._completing_timeout = 0.3
    sched.add_node(make_node("node-1", cpu_milli=4000))
    p = sched.add_pod(yk_pod("one-shot", app_id="done-app"))
    sched.wait_for_task_state("done-app", p.uid, task_mod.BOUND)
    sched.succeed_pod(p)
    deadline = time.time() + 15
    while time.time() < deadline:
        app = sched.context.get_application("done-app")
        if app is None:  # completed AND garbage-collected
            break
        time.sleep(0.05)
    assert sched.context.get_application("done-app") is None
    assert sched.core.partition.get_application("done-app") is None


def test_recovery_at_scale():
    """Recovery replay with hundreds of pre-bound pods: fast-forwarded tasks,
    exact accounting, zero rebinds (recovery_and_restart at volume)."""
    ms = MockScheduler()
    ms.init(QUEUES_YAML)
    for i in range(10):
        ms.cluster.add_node(make_node(f"node-{i}", cpu_milli=32000, memory=64 * 2**30))
    bound = []
    for i in range(300):
        p = yk_pod(f"pre-{i}", app_id=f"app-{i % 5}", cpu=500)
        p.spec.node_name = f"node-{i % 10}"
        p.status.phase = "Running"
        ms.cluster.add_pod(p)
        bound.append(p)
    pending = [ms.cluster.add_pod(yk_pod(f"new-{i}", app_id=f"app-{i % 5}", cpu=500))
               for i in range(50)]
    t0 = time.time()
    ms.start()
    try:
        for i in (0, 150, 299):
            ms.wait_for_task_state(f"app-{i % 5}", bound[i].uid, task_mod.BOUND)
        for p in pending:
            ms.wait_for_task_state(p.metadata.labels["applicationId"], p.uid,
                                   task_mod.BOUND, timeout=30)
        elapsed = time.time() - t0
        # exactly the 50 new pods were bound; the 300 recovered were not
        assert ms.bind_stats().success_count == 50
        leaf = ms.core.queues.resolve("root.default", create=False)
        assert leaf.allocated.get("cpu") == 350 * 500
        # accounting matches the cache view
        total_requested = sum(
            ms.context.schedulers_cache.get_node(f"node-{i}").requested.get("cpu")
            for i in range(10))
        assert total_requested == 350 * 500
        assert elapsed < 30
    finally:
        ms.stop()


def test_core_events_published_on_pods(sched):
    """Core allocation events surface as pod events through PublishEvents."""
    from yunikorn_tpu.common.events import get_recorder

    sched.add_node(make_node("node-1", cpu_milli=4000))
    p = sched.add_pod(yk_pod("evented"))
    sched.wait_for_task_state("app-1", p.uid, task_mod.BOUND)
    deadline = time.time() + 5
    while time.time() < deadline:
        evs = get_recorder().events(object_key=p.key(), reason="Allocated")
        if evs:
            break
        time.sleep(0.05)
    assert evs and "node-1" in evs[0].message


def test_bind_pool_bounds_thread_count(sched):
    """Round-2: binds ride a bounded worker pool, not a thread per task
    (50k tasks would otherwise spike 50k OS threads)."""
    import threading

    sched.add_nodes([make_node(f"node-{i}", cpu_milli=64000) for i in range(4)])
    before = threading.active_count()
    pods = [sched.add_pod(yk_pod(f"bp-{i}", cpu=100)) for i in range(200)]
    peak = before
    deadline = time.time() + 30
    app = None
    while time.time() < deadline:
        peak = max(peak, threading.active_count())
        app = sched.context.get_application("app-1")
        if app is not None and all(
                (t := app.get_task(p.uid)) is not None and t.state == task_mod.BOUND
                for p in pods):
            break
        time.sleep(0.05)
    assert app is not None
    assert all(app.get_task(p.uid).state == task_mod.BOUND for p in pods)
    # 32 pool workers + harness threads; far below 200
    assert peak - before <= 40, f"thread spike: {peak - before}"


MULTI_PART_YAML = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: default
  - name: gpu
    queues:
      - name: root
        queues:
          - name: default
"""


def test_multipartition_through_shim_and_rest():
    """Multi-partition end-to-end THROUGH the shim (extension beyond the
    single-partition reference shim): node labels route nodes, the partition
    annotation routes apps, pods bind only within their partition, and the
    REST partition routes expose both."""
    import json as _json
    import urllib.request

    from yunikorn_tpu.webapp.rest import RestServer

    ms = MockScheduler()
    ms.init(MULTI_PART_YAML)
    ms.start()
    rest = RestServer(ms.core, ms.context, port=0)
    port = rest.start()
    try:
        cpu_node = make_node("cpu-n0", cpu_milli=8000)
        gpu_node = make_node("gpu-n0", cpu_milli=8000,
                             labels={constants.LABEL_NODE_PARTITION: "gpu"})
        ms.add_nodes([cpu_node, gpu_node])
        gpu_pods, cpu_pods = [], []
        for i in range(4):
            gp = make_pod(f"gpu-p{i}", cpu_milli=500,
                          labels={constants.LABEL_APPLICATION_ID: "gpu-app"},
                          annotations={constants.ANNOTATION_PARTITION: "gpu"},
                          scheduler_name=constants.SCHEDULER_NAME)
            cp = make_pod(f"cpu-p{i}", cpu_milli=500,
                          labels={constants.LABEL_APPLICATION_ID: "cpu-app"},
                          scheduler_name=constants.SCHEDULER_NAME)
            gpu_pods.append(ms.add_pod(gp))
            cpu_pods.append(ms.add_pod(cp))
        for p in gpu_pods:
            ms.wait_for_task_state("gpu-app", p.uid, task_mod.BOUND, timeout=20)
            assert ms.get_pod_assignment(p) == "gpu-n0"
        for p in cpu_pods:
            ms.wait_for_task_state("cpu-app", p.uid, task_mod.BOUND, timeout=20)
            assert ms.get_pod_assignment(p) == "cpu-n0"

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                return _json.loads(r.read())

        assert sorted(get("/ws/v1/partitions")) == ["default", "gpu"]
        gpu_apps = get("/ws/v1/partition/gpu/applications")
        assert "gpu-app" in gpu_apps
        default_apps = get("/ws/v1/partition/default/applications")
        assert "cpu-app" in default_apps and "gpu-app" not in default_apps
        assert list(get("/ws/v1/partition/gpu/nodes")) == ["gpu-n0"]
    finally:
        rest.stop()
        ms.stop()
