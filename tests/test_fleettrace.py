"""Fleet trace correlation + per-pod journey ledger (round 20).

Covers the tentpole contracts: the two-ring tracer's eviction isolation
(a bind storm must never evict the cycle skeleton), the pid-parameterized
Chrome export, the FleetTracer merge (shared epoch, one pid per shard,
meta-before-data — the Perfetto-loadability fixture), the freeze/replace
lifecycle the quarantine path depends on, and the journey ledger's
exactness invariant (stage durations tile the measured e2e latency)."""
import json

from yunikorn_tpu.obs.journey import JourneyLedger
from yunikorn_tpu.obs.metrics import MetricsRegistry
from yunikorn_tpu.obs.trace import FRONT_PID, CycleTracer, FleetTracer

T0 = 1_700_000_000.0  # fixed wall-clock base: spans are pure arithmetic


# ---------------------------------------------------------------- two rings
def test_pod_storm_never_evicts_cycle_spans():
    """10k bind spans against a small tracer: the pod ring wraps, the
    cycle skeleton survives untouched (the round-14 two-ring contract)."""
    tr = CycleTracer(capacity=64, pod_capacity=128)
    for c in range(10):
        tr.add("gate", c, T0 + c, T0 + c + 0.001)
        tr.add("solve", c, T0 + c + 0.001, T0 + c + 0.002)
    for i in range(10_000):
        tr.add_pod("bind", 0, T0 + i * 1e-4, T0 + i * 1e-4 + 1e-5)
    cyc = tr.spans(pods=False)
    assert len(cyc) == 20  # every cycle span still present
    assert {s.name for s in cyc} == {"gate", "solve"}
    assert len(tr.spans(pods=True)) == 20 + 128  # pod ring capped


def test_chrome_trace_pid_parameterized():
    """pid/process_name are caller-chosen (pre-round-20 both were
    hardcoded to pid=1, so two tracers' exports collided)."""
    tr = CycleTracer()
    tr.add("gate", 1, T0, T0 + 0.01)
    doc = tr.chrome_trace(pid=7, process_name="shard 6")
    evs = doc["traceEvents"]
    assert {e["pid"] for e in evs} == {7}
    pn = [e for e in evs if e.get("name") == "process_name"]
    assert pn and pn[0]["args"]["name"] == "shard 6"


# ------------------------------------------------------------- fleet merge
def _fleet_with_work():
    fleet = FleetTracer()
    shards = [CycleTracer() for _ in range(4)]
    for k, tr in enumerate(shards):
        fleet.register(k, tr, name=f"shard {k}")
        # staggered work: shard k's cycle starts k*10ms after shard 0's
        tr.add("gate", 1, T0 + k * 0.01, T0 + k * 0.01 + 0.002)
        tr.add("solve", 1, T0 + k * 0.01 + 0.002, T0 + k * 0.01 + 0.005)
        tr.add_pod("bind", 1, T0 + k * 0.01 + 0.006, T0 + k * 0.01 + 0.007)
    fleet.add("route", 0, T0 - 0.002, T0 - 0.001, asks=8)
    return fleet, shards


def test_fleet_merge_is_valid_chrome_trace():
    """The Perfetto-loadability fixture: merged export round-trips JSON,
    every metadata event precedes every data event, every pid carries a
    process_name, every data (pid, tid) lane carries a thread_name."""
    fleet, _ = _fleet_with_work()
    doc = json.loads(json.dumps(fleet.chrome_trace()))
    evs = doc["traceEvents"]
    metas = [i for i, e in enumerate(evs) if e["ph"] == "M"]
    datas = [i for i, e in enumerate(evs) if e["ph"] != "M"]
    assert max(metas) < min(datas)
    # one pid per shard plus the front-end lane
    assert {e["pid"] for e in evs} == {FRONT_PID, 2, 3, 4, 5}
    named = {e["pid"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert {e["pid"] for e in evs} <= named
    lanes = {(e["pid"], e["tid"]) for e in evs if e["ph"] == "X"}
    tnamed = {(e["pid"], e["tid"]) for e in evs
              if e["ph"] == "M" and e["name"] == "thread_name"}
    assert lanes <= tnamed
    assert all(e["dur"] >= 0 for e in evs if e["ph"] == "X")


def test_fleet_merge_shares_one_epoch():
    """Every source subtracts the SAME epoch: shard 3's gate starts 30ms
    (in trace µs) after shard 0's, and the front-end route span — the
    earliest span — sits at ts 0."""
    fleet, _ = _fleet_with_work()
    evs = fleet.chrome_trace()["traceEvents"]
    by = {(e["pid"], e["name"]): e["ts"] for e in evs if e["ph"] == "X"}
    assert by[(FRONT_PID, "route")] == 0.0
    assert abs((by[(5, "gate")] - by[(2, "gate")]) - 30_000) < 1.0
    # data events arrive timeline-sorted (a merged trace is a timeline,
    # not a concatenation)
    ts = [e["ts"] for e in evs if e["ph"] == "X"]
    assert ts == sorted(ts)


def test_fleet_window_bounds_export():
    """window_s drops spans that ended before the window — the flight
    recorder's bounded-bundle contract."""
    import time

    fleet = FleetTracer()
    tr = CycleTracer()
    fleet.register(0, tr)
    now = time.time()
    tr.add("gate", 1, now - 3600, now - 3599)   # an hour stale
    tr.add("solve", 2, now - 1.0, now - 0.5)    # fresh
    names = {e["name"] for e in fleet.chrome_trace(window_s=30)
             ["traceEvents"] if e["ph"] == "X"}
    assert names == {"solve"}


def test_fleet_freeze_and_replace():
    """The quarantine lifecycle: freeze(k) snapshots the dying shard's
    rings (zombie writes after the freeze are dropped), replace(k)
    re-points the SAME pid at a rebuilt core's tracer on rejoin."""
    fleet = FleetTracer()
    tr = CycleTracer()
    fleet.register(1, tr, name="shard 1")
    tr.add("gate", 7, T0, T0 + 0.01)
    frozen = fleet.freeze(1)
    assert [s.name for s in frozen.spans()] == ["gate"]
    tr.add("solve", 8, T0 + 1, T0 + 2)  # the zombie unwedges and writes
    assert [s.name for s in fleet.spans()] == ["gate"]  # not merged
    # freeze is idempotent (re-entered quarantine paths)
    assert fleet.freeze(1) is frozen
    dead_pid = FRONT_PID + 1 + 1
    doc = frozen.chrome_trace(pid=dead_pid, process_name="shard 1 (dead)")
    assert {e["pid"] for e in doc["traceEvents"]} == {dead_pid}
    # rejoin: a rebuilt core's tracer takes the lane back over
    tr2 = CycleTracer()
    tr2.add("gate", 9, T0 + 5, T0 + 5.01)
    fleet.register(1, tr2, name="shard 1")
    assert [s.cycle_id for s in fleet.spans()] == [9]


# ----------------------------------------------------------------- journey
def test_journey_stage_sum_tiles_e2e_exactly():
    """The exactness invariant: four stage durations, five marks, their
    sum IS bound - admitted (same clock readings, no sampling)."""
    j = JourneyLedger()
    j.admit(["p1"], T0, shard="0")
    j.mark(["p1"], "gated", T0 + 0.004, gate_path="device")
    j.mark(["p1"], "solved", T0 + 0.010, arm="greedy")
    j.mark(["p1"], "committed", T0 + 0.011)
    j.bound("p1", T0 + 0.020)
    rec = j.get("p1")
    assert rec["outcome"] == "bound"
    # the marks telescope: the only slack is the 6-decimal rounding of
    # each stage (sub-nanosecond) — never a sampling gap
    assert abs(sum(rec["stages_ms"].values()) - rec["e2e_ms"]) < 1e-5
    want = {"gated": 4.0, "solved": 6.0, "committed": 1.0, "bound": 9.0}
    assert set(rec["stages_ms"]) == set(want)
    assert all(abs(rec["stages_ms"][k] - v) < 1e-3
               for k, v in want.items())
    assert rec["attrs"]["gate_path"] == "device"


def test_journey_missing_marks_fold_into_next_stage():
    """A pinned ask that bypassed gate+solve still tiles exactly — the
    absent stages fold into the next present one."""
    j = JourneyLedger()
    j.admit(["p2"], T0)
    j.mark(["p2"], "committed", T0 + 0.006)
    j.bound("p2", T0 + 0.010)
    rec = j.get("p2")
    assert set(rec["stages_ms"]) == {"committed", "bound"}
    assert abs(rec["stages_ms"]["committed"] - 6.0) < 1e-3
    assert abs(rec["stages_ms"]["bound"] - 4.0) < 1e-3
    assert abs(sum(rec["stages_ms"].values()) - rec["e2e_ms"]) < 1e-5


def test_journey_readmit_resets_uncommitted():
    """A repair migration re-admits the ask: the admitted mark resets
    (the e2e span restarts at re-submission) and the detour stays
    attributable via hops; committed journeys are immutable."""
    j = JourneyLedger()
    j.admit(["p3"], T0, shard="1")
    j.mark(["p3"], "gated", T0 + 0.001)
    j.annotate("p3", hop="repaired:s1->s2")
    j.admit(["p3"], T0 + 0.5, shard="2")
    j.mark(["p3"], "gated", T0 + 0.504)
    j.bound("p3", T0 + 0.510)
    rec = j.get("p3")
    assert rec["marks"]["admitted"] == round(T0 + 0.5, 6)
    assert "repaired:s1->s2" in rec["hops"]
    assert any(h.startswith("readmitted") for h in rec["hops"])
    assert abs(sum(rec["stages_ms"].values()) - rec["e2e_ms"]) < 1e-5
    # bound == committed-equivalent: a late re-admit must not reset it
    j.admit(["p3"], T0 + 9.0)
    assert j.get("p3")["marks"]["admitted"] == round(T0 + 0.5, 6)


def test_journey_skipped_then_bound_recovers():
    """skipped_fleetwide is terminal-for-now, not forever: a bind after
    the repair cooldown completes the journey, keeping the skip in hops."""
    j = JourneyLedger()
    j.admit(["p4"], T0)
    j.terminal("p4", "skipped_fleetwide")
    assert j.get("p4")["outcome"] == "skipped_fleetwide"
    j.bound("p4", T0 + 2.0)
    rec = j.get("p4")
    assert rec["outcome"] == "bound"
    assert "recovered:skipped_fleetwide" in rec["hops"]
    # and a preemption of the BOUND pod rides hops, not the outcome
    j.terminal("p4", "preempted")
    assert j.get("p4")["outcome"] == "bound"


def test_journey_bounded_capacity_and_metrics():
    """The ledger is bounded (oldest evicted past the cap, floor 64) and
    feeds the exact journey_stage_ms / terminal counter families."""
    m = MetricsRegistry()
    j = JourneyLedger(capacity=10, registry=m)  # clamps to the 64 floor
    j.admit([f"p{i}" for i in range(100)], T0)
    assert j.stats()["evicted"] == 36 and j.stats()["open"] == 64
    j.mark(["p99"], "gated", T0 + 0.002)
    j.bound("p99", T0 + 0.005)
    j.terminal("p50", "preempted")
    assert m.get("journey_completed_total").value() == 1
    assert m.get("journey_terminal_total").value(outcome="bound") == 1
    assert m.get("journey_terminal_total").value(outcome="preempted") == 1
    # stable zero series for dashboards
    assert m.get("journey_terminal_total").value(
        outcome="skipped_fleetwide") == 0
    n, total = m.get("journey_stage_ms").child_state(stage="gated")[:2]
    assert n == 1 and abs(total - 2.0) < 1e-3
