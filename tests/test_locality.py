"""Topology spread + pod (anti-)affinity tests: the placement-dependent
predicates (reference predicates e2e suite + PodTopologySpread/InterPodAffinity
plugin semantics), including in-batch count dependence.
"""
import numpy as np

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import (
    Affinity,
    PodAffinityTerm,
    TopologySpreadConstraint,
    make_node,
    make_pod,
)
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder


def make_env(nodes):
    cache = SchedulerCache()
    for n in nodes:
        cache.update_node(n)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return cache, enc


def ask_for(pod):
    return AllocationAsk(pod.uid, "app-1", get_pod_resource(pod), pod=pod)


def assignments(enc, res, batch):
    out = {}
    a = np.asarray(res.assigned)
    for i, key in enumerate(batch.ask_keys):
        idx = int(a[i])
        out[key] = enc.nodes.name_of(idx) if idx >= 0 else None
    return out


def spread_pod(name, key="zone", max_skew=1, labels=None):
    labels = labels or {"app": "web"}
    p = make_pod(name, cpu_milli=100, memory=2**20, labels=labels)
    p.spec.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=max_skew, topology_key=key, when_unsatisfiable="DoNotSchedule",
        label_selector={"matchLabels": dict(labels)})]
    return p


def anti_pod(name, topo="kubernetes.io/hostname", labels=None):
    labels = labels or {"app": "singleton"}
    p = make_pod(name, cpu_milli=100, memory=2**20, labels=labels)
    p.spec.affinity = Affinity(pod_anti_affinity_required=[PodAffinityTerm(
        label_selector={"matchLabels": dict(labels)}, topology_key=topo)])
    return p


def test_hostname_anti_affinity_one_per_node():
    cache, enc = make_env([make_node(f"n{i}", cpu_milli=8000) for i in range(4)])
    pods = [anti_pod(f"s{i}") for i in range(4)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    assert batch.locality is not None
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    nodes = [v for v in got.values() if v is not None]
    assert len(nodes) == 4
    assert len(set(nodes)) == 4  # all distinct


def test_anti_affinity_more_pods_than_nodes():
    cache, enc = make_env([make_node(f"n{i}") for i in range(3)])
    pods = [anti_pod(f"s{i}") for i in range(5)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    placed = [v for v in got.values() if v is not None]
    assert len(placed) == 3 and len(set(placed)) == 3
    assert sum(1 for v in got.values() if v is None) == 2


def test_anti_affinity_respects_existing_pods():
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    existing = make_pod("existing", cpu_milli=100, node_name="n0",
                        phase="Running", labels={"app": "singleton"})
    cache.update_pod(existing)
    enc.sync_nodes()
    p = anti_pod("new")
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "n1"


def test_zone_spread_max_skew_1():
    nodes = []
    for z in range(3):
        for i in range(2):
            nodes.append(make_node(f"z{z}-n{i}", cpu_milli=8000, labels={"zone": f"z{z}"}))
    cache, enc = make_env(nodes)
    pods = [spread_pod(f"w{i}") for i in range(6)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    assert all(v is not None for v in got.values())
    per_zone = {}
    for v in got.values():
        z = v.split("-")[0]
        per_zone[z] = per_zone.get(z, 0) + 1
    # 6 pods, 3 zones, maxSkew 1 → exactly 2 per zone
    assert per_zone == {"z0": 2, "z1": 2, "z2": 2}


def test_spread_excludes_nodes_without_key():
    cache, enc = make_env([
        make_node("zoned", labels={"zone": "a"}),
        make_node("keyless"),
    ])
    p = spread_pod("w0")
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "zoned"


def test_pod_affinity_colocates_with_existing():
    cache, enc = make_env([
        make_node("n0", labels={"zone": "a"}),
        make_node("n1", labels={"zone": "b"}),
    ])
    anchor = make_pod("anchor", cpu_milli=100, node_name="n1", phase="Running",
                      labels={"app": "db"})
    cache.update_pod(anchor)
    enc.sync_nodes()
    p = make_pod("follower", cpu_milli=100, memory=2**20, labels={"app": "web"})
    p.spec.affinity = Affinity(pod_affinity_required=[PodAffinityTerm(
        label_selector={"matchLabels": {"app": "db"}}, topology_key="zone")])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "n1"


def test_pod_affinity_self_seeding():
    # group of pods that must co-locate with each other (selector matches
    # themselves); no existing match anywhere → first pod seeds the domain
    cache, enc = make_env([
        make_node("n0", labels={"zone": "a"}, cpu_milli=8000),
        make_node("n1", labels={"zone": "b"}, cpu_milli=8000),
    ])
    pods = []
    for i in range(3):
        p = make_pod(f"cl{i}", cpu_milli=100, memory=2**20, labels={"app": "ring"})
        p.spec.affinity = Affinity(pod_affinity_required=[PodAffinityTerm(
            label_selector={"matchLabels": {"app": "ring"}}, topology_key="zone")])
        pods.append(p)
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    placed = [v for v in got.values() if v is not None]
    assert len(placed) == 3
    zones = {("a" if v == "n0" else "b") for v in placed}
    assert len(zones) == 1  # all in one zone


def test_pod_affinity_unsatisfiable_without_seed():
    cache, enc = make_env([make_node("n0", labels={"zone": "a"})])
    p = make_pod("lonely", cpu_milli=100, memory=2**20, labels={"app": "web"})
    p.spec.affinity = Affinity(pod_affinity_required=[PodAffinityTerm(
        label_selector={"matchLabels": {"app": "nonexistent"}}, topology_key="zone")])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] is None


def test_mixed_constrained_and_plain_pods():
    cache, enc = make_env([make_node(f"n{i}", cpu_milli=4000) for i in range(3)])
    pods = [anti_pod(f"s{i}") for i in range(3)]
    plain = [make_pod(f"p{i}", cpu_milli=500, memory=2**20) for i in range(6)]
    batch = enc.build_batch([ask_for(p) for p in pods + plain])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    assert all(v is not None for v in got.values())
    singleton_nodes = [got[p.uid] for p in pods]
    assert len(set(singleton_nodes)) == 3


def test_symmetric_anti_affinity_blocks_plain_pod():
    # existing anti-pod A on n0 (selector app=x); plain pod B labeled app=x
    # must avoid n0 (K8s InterPodAffinity symmetry)
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    a = anti_pod("a", labels={"app": "x"})
    a.spec.node_name = "n0"
    a.status.phase = "Running"
    cache.update_pod(a)
    enc.sync_nodes()
    b = make_pod("b", cpu_milli=100, memory=2**20, labels={"app": "x"})
    batch = enc.build_batch([ask_for(b)])
    assert batch.locality is not None
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[b.uid] == "n1"


def test_symmetric_anti_affinity_in_batch():
    # A (anti, app=x) and plain B (app=x) in the SAME batch on a 2-node
    # cluster: they must not share a node
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    a = anti_pod("a", labels={"app": "x"})
    cache.update_pod(a)  # pods enter the cache before asks flow (context does this)
    b = make_pod("b", cpu_milli=100, memory=2**20, labels={"app": "x"})
    cache.update_pod(b)
    batch = enc.build_batch([ask_for(a), ask_for(b)])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    assert got[a.uid] is not None and got[b.uid] is not None
    assert got[a.uid] != got[b.uid]


def test_cross_namespace_anti_affinity():
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    prod_pod = make_pod("prod-db", namespace="prod", cpu_milli=100,
                        node_name="n0", phase="Running", labels={"app": "db"})
    cache.update_pod(prod_pod)
    enc.sync_nodes()
    p = make_pod("dev-pod", namespace="dev", cpu_milli=100, memory=2**20)
    p.spec.affinity = Affinity(pod_anti_affinity_required=[PodAffinityTerm(
        label_selector={"matchLabels": {"app": "db"}},
        topology_key="kubernetes.io/hostname",
        namespaces=["prod"])])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "n1"


def test_spread_self_match_num():
    # pod carries a spread constraint whose selector does NOT match itself:
    # its own placement adds 0 (K8s selfMatchNum), so zone a with one existing
    # web pod is still allowed at maxSkew=1 when zone b has 0
    cache, enc = make_env([
        make_node("a0", labels={"zone": "a"}),
        make_node("b0", labels={"zone": "b"}),
    ])
    web = make_pod("web-0", cpu_milli=100, node_name="a0", phase="Running",
                   labels={"app": "web"})
    cache.update_pod(web)
    enc.sync_nodes()
    p = spread_pod("other", labels={"app": "other"})
    p.spec.topology_spread_constraints[0].label_selector = {"matchLabels": {"app": "web"}}
    # force it toward zone a via node selector; without selfMatch fix this
    # would be rejected (1+1-0 > 1)
    p.spec.node_selector = {"zone": "a"}
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "a0"


def test_locality_group_overflow_host_fallback_schedules_all():
    cache, enc = make_env([make_node(f"n{i}", labels={"zone": f"z{i}"}) for i in range(4)])
    pods = []
    # 10 distinct spread selectors -> overflow past MAX_LOCALITY_GROUPS;
    # the overflowed groups take the exact host-evaluation path instead of
    # being blocked (round-1 behavior: held pending forever)
    for i in range(10):
        p = spread_pod(f"w{i}", labels={"uniq": f"v{i}"})
        p.spec.topology_spread_constraints[0].label_selector = {
            "matchLabels": {"uniq": f"v{i}"}}
        pods.append(p)
    batch = enc.build_batch([ask_for(p) for p in pods])  # must not raise
    assert batch.locality is not None and batch.locality.fallback
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    placed = sum(1 for v in got.values() if v is not None)
    # every selector is unique → each group has one pod, no constraint binds
    assert placed == 10


# ---------------------------------------------------------------------------
# Overflow → host-fallback path (round-2: groups used to be blocked forever)
# ---------------------------------------------------------------------------

def overflow_anti_pod(name, n_terms=7, labels=None):
    """A pod with more required anti-affinity terms than MAX_CONSTRAINT_SLOTS
    (6) — not encodable in the locality tensors, must take the host path."""
    p = make_pod(name, cpu_milli=100, memory=2**20, labels=labels or {})
    p.spec.affinity = Affinity(pod_anti_affinity_required=[
        PodAffinityTerm(label_selector={"matchLabels": {f"x{i}": "t"}},
                        topology_key="kubernetes.io/hostname")
        for i in range(n_terms)
    ])
    return p


def test_overflow_constraints_fall_back_to_host_eval():
    cache, enc = make_env([make_node(f"n{i}", cpu_milli=8000) for i in range(3)])
    # existing pod on n0 matches term 3 of the overflow pod
    existing = make_pod("existing", cpu_milli=100, node_name="n0",
                        phase="Running", labels={"x3": "t"})
    cache.update_pod(existing)
    enc.sync_nodes()
    p = overflow_anti_pod("big")
    batch = enc.build_batch([ask_for(p)])
    assert batch.locality is not None and batch.locality.fallback
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    # scheduled (not starved), and NOT on the node its 4th term forbids
    assert got[p.uid] is not None
    assert got[p.uid] != "n0"


def test_overflow_group_serialized_one_pod_per_solve():
    """Two pods of one overflowed group that anti-affine each other: only one
    may land per solve (static host mask can't see intra-batch placements);
    the second schedules next cycle once the first is visible in the cache."""
    cache, enc = make_env([make_node(f"n{i}", cpu_milli=8000) for i in range(3)])
    pods = [overflow_anti_pod(f"s{i}", labels={"x0": "t"}) for i in range(2)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    placed = {k: v for k, v in got.items() if v is not None}
    assert len(placed) == 1
    # bind the first, re-encode, second must land on a DIFFERENT node
    first_key, first_node = next(iter(placed.items()))
    first_pod = next(p for p in pods if p.uid == first_key)
    first_pod.spec.node_name = first_node
    first_pod.status.phase = "Running"
    cache.update_pod(first_pod)
    enc.sync_nodes()
    second = next(p for p in pods if p.uid != first_key)
    batch2 = enc.build_batch([ask_for(second)])
    res2 = solve_batch(batch2, enc.nodes)
    got2 = assignments(enc, res2, batch2)
    assert got2[second.uid] is not None
    assert got2[second.uid] != first_node


def test_overflow_spread_host_semantics():
    """Host fallback also enforces DoNotSchedule spread exactly: with skew 1
    and 2 pods already in zone a, the next must go to zone b."""
    nodes = [make_node("a0", labels={"zone": "a"}),
             make_node("b0", labels={"zone": "b"})]
    cache, enc = make_env(nodes)
    for i in range(2):
        ex = make_pod(f"e{i}", cpu_milli=100, node_name="a0", phase="Running",
                      labels={"app": "web"})
        cache.update_pod(ex)
    enc.sync_nodes()
    p = spread_pod("w0")  # zone spread, maxSkew 1, selector app=web
    # add 6 anti terms to force overflow alongside the spread constraint
    p.spec.affinity = Affinity(pod_anti_affinity_required=[
        PodAffinityTerm(label_selector={"matchLabels": {f"y{i}": "t"}},
                        topology_key="kubernetes.io/hostname")
        for i in range(6)
    ])
    batch = enc.build_batch([ask_for(p)])
    assert batch.locality is not None and batch.locality.fallback
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    assert got[p.uid] == "b0"  # 2 in a, 0 in b, skew 1 → must balance


def test_group_cache_is_bounded():
    cache, enc = make_env([make_node("n0")])
    enc._group_cache_max = 4
    pods = [make_pod(f"p{i}", cpu_milli=100, memory=2**20,
                     node_selector={"shard": f"s{i}"}) for i in range(10)]
    for p in pods:
        enc.build_batch([ask_for(p)])
    assert len(enc._group_cache) <= 4


def test_symmetry_holder_labels_not_matching_own_term():
    """An existing pod E HOLDS an anti-affinity term t whose selector matches
    incoming pod N, but E's own labels do NOT match t. N also carries t.
    Symmetry must still keep N off E's node — the primary slot (which counts
    pods MATCHING t) cannot stand in for the holder check."""
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    term = PodAffinityTerm(label_selector={"matchLabels": {"app": "web"}},
                           topology_key="kubernetes.io/hostname")
    existing = make_pod("holder", cpu_milli=100, node_name="n0",
                        phase="Running", labels={"app": "db"})
    existing.spec.affinity = Affinity(pod_anti_affinity_required=[term])
    cache.update_pod(existing)
    enc.sync_nodes()
    incoming = make_pod("web-pod", cpu_milli=100, memory=2**20,
                        labels={"app": "web"})
    incoming.spec.affinity = Affinity(pod_anti_affinity_required=[
        PodAffinityTerm(label_selector={"matchLabels": {"app": "web"}},
                        topology_key="kubernetes.io/hostname")])
    batch = enc.build_batch([ask_for(incoming)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[incoming.uid] == "n1"


def test_symmetry_holder_not_matching_own_term_host_fallback():
    """Same scenario through the overflow host-evaluation path."""
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    term = PodAffinityTerm(label_selector={"matchLabels": {"app": "web"}},
                           topology_key="kubernetes.io/hostname")
    existing = make_pod("holder", cpu_milli=100, node_name="n0",
                        phase="Running", labels={"app": "db"})
    existing.spec.affinity = Affinity(pod_anti_affinity_required=[term])
    cache.update_pod(existing)
    enc.sync_nodes()
    incoming = overflow_anti_pod("web-pod", labels={"app": "web"})
    incoming.spec.affinity.pod_anti_affinity_required.append(
        PodAffinityTerm(label_selector={"matchLabels": {"app": "web"}},
                        topology_key="kubernetes.io/hostname"))
    batch = enc.build_batch([ask_for(incoming)])
    assert batch.locality is not None and batch.locality.fallback
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[incoming.uid] == "n1"


# ---------------------------------------------------------------------------
# Soft locality: ScheduleAnyway spread + preferred pod (anti-)affinity scoring
# ---------------------------------------------------------------------------

def soft_spread_pod(name, key="zone", labels=None):
    labels = labels or {"app": "web"}
    p = make_pod(name, cpu_milli=100, memory=2**20, labels=labels)
    p.spec.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1, topology_key=key, when_unsatisfiable="ScheduleAnyway",
        label_selector={"matchLabels": dict(labels)})]
    return p


def test_schedule_anyway_prefers_balance():
    nodes = [make_node("a0", labels={"zone": "a"}),
             make_node("b0", labels={"zone": "b"})]
    cache, enc = make_env(nodes)
    for i in range(2):
        ex = make_pod(f"e{i}", cpu_milli=100, node_name="a0", phase="Running",
                      labels={"app": "web"})
        cache.update_pod(ex)
    enc.sync_nodes()
    p = soft_spread_pod("w0")
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes, policy="spread")
    # 2 in zone a, 0 in b → prefers b (but would not require it)
    assert assignments(enc, res, batch)[p.uid] == "b0"


def test_schedule_anyway_does_not_require():
    """Unlike DoNotSchedule, ScheduleAnyway must place the pod even when the
    preferred domain is infeasible."""
    nodes = [make_node("a0", labels={"zone": "a"}),
             make_node("b0", cpu_milli=100, labels={"zone": "b"})]  # tiny node
    cache, enc = make_env(nodes)
    for i in range(2):
        ex = make_pod(f"e{i}", cpu_milli=100, node_name="a0", phase="Running",
                      labels={"app": "web"})
        cache.update_pod(ex)
    enc.sync_nodes()
    p = soft_spread_pod("w0")
    p.spec.containers[0].resources_requests["cpu"] = "2000m"  # b0 can't fit
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes, policy="spread")
    # zone b is preferred but infeasible → still schedules (in zone a)
    assert assignments(enc, res, batch)[p.uid] == "a0"


def test_schedule_anyway_balances_within_batch():
    nodes = [make_node("a0", cpu_milli=8000, labels={"zone": "a"}),
             make_node("b0", cpu_milli=8000, labels={"zone": "b"})]
    cache, enc = make_env(nodes)
    pods = [soft_spread_pod(f"w{i}") for i in range(4)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes, policy="spread")
    got = assignments(enc, res, batch)
    assert all(v is not None for v in got.values())
    per_zone = {"a0": 0, "b0": 0}
    for v in got.values():
        per_zone[v] += 1
    # dynamic counts steer the batch toward balance
    assert per_zone["a0"] == 2 and per_zone["b0"] == 2


def test_preferred_pod_affinity_colocates():
    cache, enc = make_env([
        make_node("n0", labels={"zone": "a"}),
        make_node("n1", labels={"zone": "b"}),
    ])
    db = make_pod("db", cpu_milli=100, node_name="n1", phase="Running",
                  labels={"app": "db"})
    cache.update_pod(db)
    enc.sync_nodes()
    p = make_pod("web", cpu_milli=100, memory=2**20, labels={"app": "web"})
    p.spec.affinity = Affinity(pod_affinity_preferred=[
        (100, PodAffinityTerm(label_selector={"matchLabels": {"app": "db"}},
                              topology_key="zone"))])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes, policy="spread")
    assert assignments(enc, res, batch)[p.uid] == "n1"


def test_preferred_anti_affinity_avoids():
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    noisy = make_pod("noisy", cpu_milli=100, node_name="n0", phase="Running",
                     labels={"app": "noisy"})
    cache.update_pod(noisy)
    enc.sync_nodes()
    p = make_pod("quiet", cpu_milli=100, memory=2**20)
    p.spec.affinity = Affinity(pod_anti_affinity_preferred=[
        (100, PodAffinityTerm(label_selector={"matchLabels": {"app": "noisy"}},
                              topology_key="kubernetes.io/hostname"))])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes, policy="spread")
    assert assignments(enc, res, batch)[p.uid] == "n1"


def test_soft_spill_static_host_scoring():
    """Soft preferences that spill the slot budget (hard slots full) are
    statically host-scored into g_host_soft instead of dropped."""
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    db = make_pod("db", cpu_milli=100, node_name="n1", phase="Running",
                  labels={"app": "db"})
    cache.update_pod(db)
    enc.sync_nodes()
    p = make_pod("busy", cpu_milli=100, memory=2**20)
    # 6 hard anti terms fill MAX_CONSTRAINT_SLOTS; the preference must spill
    p.spec.affinity = Affinity(
        pod_anti_affinity_required=[
            PodAffinityTerm(label_selector={"matchLabels": {f"z{i}": "t"}},
                            topology_key="kubernetes.io/hostname")
            for i in range(6)],
        pod_affinity_preferred=[
            (100, PodAffinityTerm(label_selector={"matchLabels": {"app": "db"}},
                                  topology_key="kubernetes.io/hostname"))],
    )
    batch = enc.build_batch([ask_for(p)])
    assert batch.locality is not None and batch.locality.soft_static
    assert batch.g_host_soft is not None
    res = solve_batch(batch, enc.nodes, policy="spread")
    assert assignments(enc, res, batch)[p.uid] == "n1"
