"""Topology spread + pod (anti-)affinity tests: the placement-dependent
predicates (reference predicates e2e suite + PodTopologySpread/InterPodAffinity
plugin semantics), including in-batch count dependence.
"""
import numpy as np

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import (
    Affinity,
    PodAffinityTerm,
    TopologySpreadConstraint,
    make_node,
    make_pod,
)
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder


def make_env(nodes):
    cache = SchedulerCache()
    for n in nodes:
        cache.update_node(n)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return cache, enc


def ask_for(pod):
    return AllocationAsk(pod.uid, "app-1", get_pod_resource(pod), pod=pod)


def assignments(enc, res, batch):
    out = {}
    a = np.asarray(res.assigned)
    for i, key in enumerate(batch.ask_keys):
        idx = int(a[i])
        out[key] = enc.nodes.name_of(idx) if idx >= 0 else None
    return out


def spread_pod(name, key="zone", max_skew=1, labels=None):
    labels = labels or {"app": "web"}
    p = make_pod(name, cpu_milli=100, memory=2**20, labels=labels)
    p.spec.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=max_skew, topology_key=key, when_unsatisfiable="DoNotSchedule",
        label_selector={"matchLabels": dict(labels)})]
    return p


def anti_pod(name, topo="kubernetes.io/hostname", labels=None):
    labels = labels or {"app": "singleton"}
    p = make_pod(name, cpu_milli=100, memory=2**20, labels=labels)
    p.spec.affinity = Affinity(pod_anti_affinity_required=[PodAffinityTerm(
        label_selector={"matchLabels": dict(labels)}, topology_key=topo)])
    return p


def test_hostname_anti_affinity_one_per_node():
    cache, enc = make_env([make_node(f"n{i}", cpu_milli=8000) for i in range(4)])
    pods = [anti_pod(f"s{i}") for i in range(4)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    assert batch.locality is not None
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    nodes = [v for v in got.values() if v is not None]
    assert len(nodes) == 4
    assert len(set(nodes)) == 4  # all distinct


def test_anti_affinity_more_pods_than_nodes():
    cache, enc = make_env([make_node(f"n{i}") for i in range(3)])
    pods = [anti_pod(f"s{i}") for i in range(5)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    placed = [v for v in got.values() if v is not None]
    assert len(placed) == 3 and len(set(placed)) == 3
    assert sum(1 for v in got.values() if v is None) == 2


def test_anti_affinity_respects_existing_pods():
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    existing = make_pod("existing", cpu_milli=100, node_name="n0",
                        phase="Running", labels={"app": "singleton"})
    cache.update_pod(existing)
    enc.sync_nodes()
    p = anti_pod("new")
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "n1"


def test_zone_spread_max_skew_1():
    nodes = []
    for z in range(3):
        for i in range(2):
            nodes.append(make_node(f"z{z}-n{i}", cpu_milli=8000, labels={"zone": f"z{z}"}))
    cache, enc = make_env(nodes)
    pods = [spread_pod(f"w{i}") for i in range(6)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    assert all(v is not None for v in got.values())
    per_zone = {}
    for v in got.values():
        z = v.split("-")[0]
        per_zone[z] = per_zone.get(z, 0) + 1
    # 6 pods, 3 zones, maxSkew 1 → exactly 2 per zone
    assert per_zone == {"z0": 2, "z1": 2, "z2": 2}


def test_spread_excludes_nodes_without_key():
    cache, enc = make_env([
        make_node("zoned", labels={"zone": "a"}),
        make_node("keyless"),
    ])
    p = spread_pod("w0")
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "zoned"


def test_pod_affinity_colocates_with_existing():
    cache, enc = make_env([
        make_node("n0", labels={"zone": "a"}),
        make_node("n1", labels={"zone": "b"}),
    ])
    anchor = make_pod("anchor", cpu_milli=100, node_name="n1", phase="Running",
                      labels={"app": "db"})
    cache.update_pod(anchor)
    enc.sync_nodes()
    p = make_pod("follower", cpu_milli=100, memory=2**20, labels={"app": "web"})
    p.spec.affinity = Affinity(pod_affinity_required=[PodAffinityTerm(
        label_selector={"matchLabels": {"app": "db"}}, topology_key="zone")])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "n1"


def test_pod_affinity_self_seeding():
    # group of pods that must co-locate with each other (selector matches
    # themselves); no existing match anywhere → first pod seeds the domain
    cache, enc = make_env([
        make_node("n0", labels={"zone": "a"}, cpu_milli=8000),
        make_node("n1", labels={"zone": "b"}, cpu_milli=8000),
    ])
    pods = []
    for i in range(3):
        p = make_pod(f"cl{i}", cpu_milli=100, memory=2**20, labels={"app": "ring"})
        p.spec.affinity = Affinity(pod_affinity_required=[PodAffinityTerm(
            label_selector={"matchLabels": {"app": "ring"}}, topology_key="zone")])
        pods.append(p)
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    placed = [v for v in got.values() if v is not None]
    assert len(placed) == 3
    zones = {("a" if v == "n0" else "b") for v in placed}
    assert len(zones) == 1  # all in one zone


def test_pod_affinity_unsatisfiable_without_seed():
    cache, enc = make_env([make_node("n0", labels={"zone": "a"})])
    p = make_pod("lonely", cpu_milli=100, memory=2**20, labels={"app": "web"})
    p.spec.affinity = Affinity(pod_affinity_required=[PodAffinityTerm(
        label_selector={"matchLabels": {"app": "nonexistent"}}, topology_key="zone")])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] is None


def test_mixed_constrained_and_plain_pods():
    cache, enc = make_env([make_node(f"n{i}", cpu_milli=4000) for i in range(3)])
    pods = [anti_pod(f"s{i}") for i in range(3)]
    plain = [make_pod(f"p{i}", cpu_milli=500, memory=2**20) for i in range(6)]
    batch = enc.build_batch([ask_for(p) for p in pods + plain])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    assert all(v is not None for v in got.values())
    singleton_nodes = [got[p.uid] for p in pods]
    assert len(set(singleton_nodes)) == 3


def test_symmetric_anti_affinity_blocks_plain_pod():
    # existing anti-pod A on n0 (selector app=x); plain pod B labeled app=x
    # must avoid n0 (K8s InterPodAffinity symmetry)
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    a = anti_pod("a", labels={"app": "x"})
    a.spec.node_name = "n0"
    a.status.phase = "Running"
    cache.update_pod(a)
    enc.sync_nodes()
    b = make_pod("b", cpu_milli=100, memory=2**20, labels={"app": "x"})
    batch = enc.build_batch([ask_for(b)])
    assert batch.locality is not None
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[b.uid] == "n1"


def test_symmetric_anti_affinity_in_batch():
    # A (anti, app=x) and plain B (app=x) in the SAME batch on a 2-node
    # cluster: they must not share a node
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    a = anti_pod("a", labels={"app": "x"})
    cache.update_pod(a)  # pods enter the cache before asks flow (context does this)
    b = make_pod("b", cpu_milli=100, memory=2**20, labels={"app": "x"})
    cache.update_pod(b)
    batch = enc.build_batch([ask_for(a), ask_for(b)])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    assert got[a.uid] is not None and got[b.uid] is not None
    assert got[a.uid] != got[b.uid]


def test_cross_namespace_anti_affinity():
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    prod_pod = make_pod("prod-db", namespace="prod", cpu_milli=100,
                        node_name="n0", phase="Running", labels={"app": "db"})
    cache.update_pod(prod_pod)
    enc.sync_nodes()
    p = make_pod("dev-pod", namespace="dev", cpu_milli=100, memory=2**20)
    p.spec.affinity = Affinity(pod_anti_affinity_required=[PodAffinityTerm(
        label_selector={"matchLabels": {"app": "db"}},
        topology_key="kubernetes.io/hostname",
        namespaces=["prod"])])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "n1"


def test_spread_self_match_num():
    # pod carries a spread constraint whose selector does NOT match itself:
    # its own placement adds 0 (K8s selfMatchNum), so zone a with one existing
    # web pod is still allowed at maxSkew=1 when zone b has 0
    cache, enc = make_env([
        make_node("a0", labels={"zone": "a"}),
        make_node("b0", labels={"zone": "b"}),
    ])
    web = make_pod("web-0", cpu_milli=100, node_name="a0", phase="Running",
                   labels={"app": "web"})
    cache.update_pod(web)
    enc.sync_nodes()
    p = spread_pod("other", labels={"app": "other"})
    p.spec.topology_spread_constraints[0].label_selector = {"matchLabels": {"app": "web"}}
    # force it toward zone a via node selector; without selfMatch fix this
    # would be rejected (1+1-0 > 1)
    p.spec.node_selector = {"zone": "a"}
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "a0"


def test_locality_group_overflow_blocks_not_crashes():
    cache, enc = make_env([make_node(f"n{i}", labels={"zone": f"z{i}"}) for i in range(4)])
    pods = []
    # 10 distinct spread selectors -> overflow past MAX_LOCALITY_GROUPS
    for i in range(10):
        p = spread_pod(f"w{i}", labels={"uniq": f"v{i}"})
        p.spec.topology_spread_constraints[0].label_selector = {
            "matchLabels": {"uniq": f"v{i}"}}
        pods.append(p)
    batch = enc.build_batch([ask_for(p) for p in pods])  # must not raise
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    placed = sum(1 for v in got.values() if v is not None)
    # the encodable groups scheduled; overflow groups held pending
    assert 0 < placed < 10
