"""Placement-rule matrix: the configured rule chain (provided/user/group/
tag/fixed, filters, create flags, nested parents) resolved against a queue
tree — the yunikorn-core placement-manager semantics the reference shim
delegates to (reference placement tests in yunikorn-core's
pkg/scheduler/placement; shim side context.go:922-1023).
"""
import pytest

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.si import AddApplicationRequest, UserGroupInfo
from yunikorn_tpu.core.placement import (PlacementEngine, RuleFilter,
                                         apply_namespace_quota,
                                         parse_placement_rules)
from yunikorn_tpu.core.queues import QueueTree, parse_queues_yaml

YAML = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: static
          - name: teams
            parent: true
            queues:
              - name: blue
"""


def tree():
    return QueueTree(parse_queues_yaml(YAML))


def add_req(queue="", user="alice", groups=("dev",), tags=None):
    return AddApplicationRequest(
        application_id="app-1", queue_name=queue,
        user=UserGroupInfo(user=user, groups=list(groups)),
        tags=dict(tags or {}))


def engine(*rule_docs):
    return PlacementEngine(parse_placement_rules(
        {"placementrules": list(rule_docs)}))


# ---------------------------------------------------------------- rule kinds

def test_provided_rule_resolves_named_queue():
    e = engine({"name": "provided", "create": False})
    leaf = e.place(add_req(queue="root.static"), tree())
    assert leaf is not None and leaf.full_name == "root.static"


def test_provided_rule_create_false_rejects_unknown():
    e = engine({"name": "provided", "create": False})
    assert e.place(add_req(queue="root.nope"), tree()) is None


def test_provided_rule_create_true_makes_queue():
    e = engine({"name": "provided", "create": True})
    leaf = e.place(add_req(queue="root.newq"), tree())
    assert leaf is not None and leaf.full_name == "root.newq"


def test_user_rule_sanitizes_dots():
    e = engine({"name": "user"})
    leaf = e.place(add_req(user="first.last"), tree())
    assert leaf.full_name == "root.first_dot_last"


def test_group_rule_uses_primary_group():
    e = engine({"name": "group"})
    leaf = e.place(add_req(groups=("ops", "dev")), tree())
    assert leaf.full_name == "root.ops"


def test_group_rule_no_groups_falls_through_to_next():
    e = engine({"name": "group"}, {"name": "fixed", "value": "root.static"})
    leaf = e.place(add_req(groups=()), tree())
    assert leaf.full_name == "root.static"


def test_tag_rule_namespace():
    e = engine({"name": "tag", "value": "namespace"})
    leaf = e.place(add_req(tags={constants.APP_TAG_NAMESPACE: "team-ns"}), tree())
    assert leaf.full_name == "root.team-ns"


def test_tag_rule_missing_tag_skips():
    e = engine({"name": "tag", "value": "custom-key"},
               {"name": "fixed", "value": "root.static"})
    assert e.place(add_req(), tree()).full_name == "root.static"


def test_fixed_rule_always_places():
    e = engine({"name": "fixed", "value": "root.static"})
    assert e.place(add_req(), tree()).full_name == "root.static"


def test_unknown_rule_name_ignored():
    e = engine({"name": "bogus"}, {"name": "fixed", "value": "root.static"})
    assert len(e.rules) == 1
    assert e.place(add_req(), tree()).full_name == "root.static"


# ------------------------------------------------------------------ filters

@pytest.mark.parametrize("filt,user,groups,placed", [
    # allow list: only listed users pass
    ({"type": "allow", "users": ["alice"]}, "alice", ("dev",), True),
    ({"type": "allow", "users": ["alice"]}, "bob", ("dev",), False),
    # deny list: listed users are blocked
    ({"type": "deny", "users": ["alice"]}, "alice", ("dev",), False),
    ({"type": "deny", "users": ["alice"]}, "bob", ("dev",), True),
    # group filters
    ({"type": "allow", "groups": ["dev"]}, "zoe", ("dev",), True),
    ({"type": "allow", "groups": ["dev"]}, "zoe", ("ops",), False),
    # single regex entry (non-plain) matches the whole name
    ({"type": "allow", "users": ["^data-.*$"]}, "data-eng", (), True),
    ({"type": "allow", "users": ["^data-.*$"]}, "web-eng", (), False),
    # empty filter matches everyone
    ({}, "anyone", (), True),
])
def test_rule_filter_matrix(filt, user, groups, placed):
    e = engine({"name": "fixed", "value": "root.static", "filter": filt})
    leaf = e.place(add_req(user=user, groups=groups), tree())
    assert (leaf is not None) is placed


def test_filter_invalid_regex_never_matches():
    f = RuleFilter(type="allow", users=["[invalid"])
    assert not f.allows("anything", [])


# ------------------------------------------------------------ nested parents

def test_user_rule_under_tag_parent():
    e = engine({"name": "user",
                "parent": {"name": "tag", "value": "namespace"}})
    leaf = e.place(add_req(user="alice",
                           tags={constants.APP_TAG_NAMESPACE: "teams"}), tree())
    assert leaf.full_name == "root.teams.alice"


def test_parent_rule_failure_fails_the_whole_rule():
    e = engine({"name": "user", "parent": {"name": "tag", "value": "missing"}},
               {"name": "fixed", "value": "root.static"})
    leaf = e.place(add_req(user="alice"), tree())
    assert leaf.full_name == "root.static"      # fell through, not root.alice


def test_qualified_leaf_cannot_be_reparented():
    # provided gives a fully-qualified name; nesting it under a parent is
    # ambiguous and must fail the rule
    e = engine({"name": "provided",
                "parent": {"name": "fixed", "value": "root.teams"}})
    assert e.place(add_req(queue="root.static"), tree()) is None


def test_parent_queue_must_yield_leaf():
    # placing into a parent-type queue (root.teams has children) fails
    e = engine({"name": "fixed", "value": "root.teams"})
    assert e.place(add_req(), tree()) is None


# ------------------------------------------------------- namespace annotations

def test_namespace_quota_applied_to_dynamic_queue_only():
    t = tree()
    e = engine({"name": "tag", "value": "namespace"})
    req = add_req(tags={
        constants.APP_TAG_NAMESPACE: "quota-ns",
        constants.NAMESPACE_QUOTA: '{"cpu": "2", "memory": "1Gi"}',
        constants.NAMESPACE_MAX_APPS: "3",
    })
    leaf = e.place(req, t)
    assert leaf.dynamic
    apply_namespace_quota(leaf, req)
    assert leaf.config.max_resource.get("cpu") == 2000
    assert leaf.config.max_resource.get("memory") == 2**30
    assert leaf.config.max_applications == 3
    # static queues keep their yaml config untouched
    static = t.resolve("root.static", create=False)
    before = static.config.max_resource
    apply_namespace_quota(static, req)
    assert static.config.max_resource is before


def test_namespace_quota_malformed_json_ignored():
    t = tree()
    e = engine({"name": "tag", "value": "namespace"})
    req = add_req(tags={
        constants.APP_TAG_NAMESPACE: "bad-ns",
        constants.NAMESPACE_QUOTA: "not json",
        constants.NAMESPACE_MAX_APPS: "many",
    })
    leaf = e.place(req, t)
    apply_namespace_quota(leaf, req)            # must not raise
    assert leaf.config.max_applications in (0, None)
