"""Round-21 async front end: per-shard delivery queues, the device-resident
ledger mirror, and the sharded bind pool.

  * delivery: FIFO per queue, flush barriers, fence-drops-backlog +
    revive-restores (the quarantine/rejoin hooks);
  * the pre-detection stall regression: a front-end call into a WEDGED
    shard (its core lock held by a stuck cycle) returns bounded-fast
    BEFORE the failover supervisor has noticed anything;
  * backpressure: a queue past its high-water mark sheds NEW unpinned
    asks to the least-loaded survivor — and no ask is ever lost;
  * the ledger mirror: bit-equality against GlobalQuotaLedger confirmed
    usage (the commit-time-authority invariant), including across a
    quarantine, and the conservative direction of provably_exceeds;
  * reserve_many: sequentially exact vs N reserve() calls;
  * ShardedBindPool: per-key FIFO ordering with cross-key parallelism.

Everything here is deterministic (wedges are a held core lock, not a
timed fault), so the suite stays in tier-1 without @pytest.mark.slow.
"""
import threading
import time
import zlib

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import (
    AddApplicationRequest,
    AllocationAsk,
    AllocationRequest,
    ApplicationRequest,
    NodeAction,
    NodeInfo,
    NodeRequest,
    RegisterResourceManagerRequest,
    ResourceManagerCallback,
    UserGroupInfo,
)
from yunikorn_tpu.core.delivery import ShardDeliveryQueue
from yunikorn_tpu.core.shard import GlobalQuotaLedger, ShardedCoreScheduler
from yunikorn_tpu.ops.ledger_mirror import DeviceUsageMirror
from yunikorn_tpu.robustness.failover import FailoverOptions
from yunikorn_tpu.utils.workers import ShardedBindPool

# failover pushed out of the picture: these tests exercise the window
# BEFORE detection, so nothing must quarantine underneath them
INERT = FailoverOptions(stale_budget_s=3600.0, probe_interval_s=3600.0,
                        rejoin_after_s=3600.0)


class Recorder(ResourceManagerCallback):
    def __init__(self):
        self.new = []
        self.released = []
        self.accepted_apps = []
        self.rejected_apps = []

    def update_allocation(self, response):
        self.new.extend(response.new)
        self.released.extend(response.released)

    def update_application(self, response):
        self.accepted_apps.extend(a.application_id for a in response.accepted)
        self.rejected_apps.extend(
            (r.application_id, r.reason) for r in response.rejected)

    def update_node(self, response):
        pass

    def predicates(self, args):
        return None

    def preemption_predicates(self, args):
        return []

    def send_event(self, events):
        pass

    def update_container_scheduling_state(self, request):
        pass

    def get_state_dump(self):
        return "{}"


def _front(n=2, nodes=4, cpu=8000, high_water=1024):
    cache = SchedulerCache()
    cb = Recorder()
    front = ShardedCoreScheduler(cache, n, interval=0.03,
                                 failover_options=INERT,
                                 delivery_high_water=high_water)
    front.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="t", policy_group="queues",
                                      config=""), cb)
    infos = []
    for i in range(nodes):
        node = make_node(f"an-{i}", cpu_milli=cpu)
        cache.update_node(node)
        infos.append(NodeInfo(node_id=node.name, action=NodeAction.CREATE,
                              node=node))
    front.update_node(NodeRequest(nodes=infos))
    front.flush()
    return front, cb


def _submit_app(front, app_id):
    front.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id=app_id, queue_name="root.default",
        user=UserGroupInfo(user="alice", groups=["devs"]))]))


def _ask(app_id, key, cpu=500):
    pod = make_pod(key, cpu_milli=cpu, memory=2 ** 28)
    return AllocationAsk(allocation_key=key, application_id=app_id,
                         resource=get_pod_resource(pod), pod=pod)


def _apps_homed(n, shard, prefix, count):
    """App ids whose home shard (crc32 routing) is `shard`."""
    return [a for a in (f"{prefix}-{i}" for i in range(512))
            if zlib.crc32(a.encode()) % n == shard][:count]


# ----------------------------------------------------------- delivery queues
class _SpyCore:
    def __init__(self, block_on=None):
        self.calls = []
        self._block = block_on  # threading.Event the core waits on

    def poke(self, *args):
        if self._block is not None:
            self._block.wait()
        self.calls.append(("poke",) + args)

    def other(self, *args):
        self.calls.append(("other",) + args)


def test_delivery_queue_is_fifo_and_flush_drains():
    core = _SpyCore()
    q = ShardDeliveryQueue(0, core)
    try:
        for i in range(16):
            assert q.enqueue("poke" if i % 2 == 0 else "other", i)
        assert q.flush(timeout=5.0)
        assert [c[1] for c in core.calls] == list(range(16))
        st = q.stats()
        assert st["enqueued"] == 16 and st["delivered"] == 16
        assert st["depth"] == 0 and st["dropped"] == 0
    finally:
        q.stop()


def test_delivery_queue_fence_drops_backlog_and_revive_restores():
    gate = threading.Event()
    core = _SpyCore(block_on=gate)
    q = ShardDeliveryQueue(0, core)
    try:
        for i in range(5):
            q.enqueue("poke", i)
        time.sleep(0.1)  # pump picks item 0 and blocks on the gate
        dropped = q.fence()
        # the inflight delivery is NOT in the dropped backlog (the zombie
        # core consumed it); the queued remainder is returned for re-derive
        assert [a[0] for _m, a in dropped] == [1, 2, 3, 4]
        assert q.dead
        assert q.enqueue("poke", 99) is False  # fenced: drop, never block
        assert q.flush(timeout=0.2) is False
        core2 = _SpyCore()
        q.revive(core2)
        assert not q.dead
        assert q.enqueue("other", 7)
        assert q.flush(timeout=5.0)
        assert core2.calls == [("other", 7)]
        gate.set()  # unwedge the zombie pump: it must exit on stale epoch
        time.sleep(0.1)
        assert q.stats()["delivered"] == 1  # only the post-revive delivery
    finally:
        gate.set()
        q.stop()


def test_front_calls_bounded_while_shard_wedged_pre_detection():
    """THE round-18 pre-detection stall, pinned dead: with one shard's
    core lock held by a stuck cycle (the supervisor has detected nothing),
    every front-end call into that shard still returns in milliseconds —
    it lands on the delivery queue, not on the dead lock."""
    front, cb = _front(n=2, nodes=4)
    try:
        victim = 0
        apps = _apps_homed(2, victim, "wapp", 3)
        # wedge: the cycle thread equivalent — hold the victim core's lock
        front.shards[victim]._lock.acquire()  # RMutex: returns None
        try:
            t0 = time.time()
            for i, app in enumerate(apps):
                _submit_app(front, app)
                front.update_allocation(AllocationRequest(
                    asks=[_ask(app, f"wpod-{i}")]))
            front.update_node(NodeRequest(nodes=[]))
            dt = time.time() - t0
            # bounded: 7 calls into a wedged shard, well under a second
            # (pre-async each would block until the lock freed)
            assert dt < 1.0, f"front-end calls stalled {dt:.2f}s on a wedge"
            assert front.delivery[victim].depth() > 0
        finally:
            front.shards[victim]._lock.release()
        # after the wedge clears, the backlog drains and asks place
        assert front.flush(timeout=10.0)
        front.schedule_once()
        got = {a.allocation_key for a in cb.new}
        assert got == {f"wpod-{i}" for i in range(len(apps))}
    finally:
        front.stop()


def test_queue_overflow_sheds_to_survivor_without_losing_asks():
    front, cb = _front(n=2, nodes=4, high_water=3)
    try:
        victim = 0
        apps = _apps_homed(2, victim, "sapp", 8)
        for app in apps:
            _submit_app(front, app)
        front.flush()
        front.shards[victim]._lock.acquire()  # RMutex: returns None
        try:
            for i, app in enumerate(apps):
                front.update_allocation(AllocationRequest(
                    asks=[_ask(app, f"spod-{i}", cpu=100)]))
            # the victim queue saturated at its high-water mark; the
            # overflow went to the survivor instead of deepening it
            shed = front.obs.get("shard_queue_shed_total").value(
                shard=str(victim))
            assert shed > 0, "no asks shed past the high-water mark"
            # the wedged queue absorbed strictly fewer asks than submitted
            # (shedding only reroutes when the survivor is shallower, so
            # a burst may still land some asks home — but never all)
            assert front.delivery[victim].depth() < len(apps)
        finally:
            front.shards[victim]._lock.release()
        assert front.flush(timeout=10.0)
        front.schedule_once()
        # every ask placed exactly once: shed rerouted, never dropped
        got = sorted(a.allocation_key for a in cb.new)
        assert got == sorted(f"spod-{i}" for i in range(len(apps)))
    finally:
        front.stop()


# ------------------------------------------------------------- ledger mirror
def _charges(tid, lim, amt):
    return [(tid, [("cpu", lim)], [("cpu", amt)])]


def test_mirror_bit_equal_to_ledger_through_lifecycle():
    ledger = GlobalQuotaLedger()
    mirror = DeviceUsageMirror(2)
    ledger.attach_mirror(mirror)
    for i in range(6):
        assert ledger.reserve(f"k{i}", _charges("q:root.a", 100_000, 100))
        ledger.commit(f"k{i}", _charges("q:root.a", 100_000, 100))
    ledger.commit("forced", _charges("u:alice", 10_000, 7))  # force path
    for i in range(0, 6, 2):
        ledger.release(f"k{i}")
    assert mirror.divergence(ledger) == 0
    assert mirror.host_usage() == ledger.usage_snapshot()
    assert mirror.host_usage() == {"q:root.a": {"cpu": 300},
                                   "u:alice": {"cpu": 7}}
    # a reservation alone must NOT appear in the mirror (confirmed only)
    assert ledger.reserve("pend", _charges("q:root.a", 100_000, 50))
    assert mirror.divergence(ledger) == 0
    ledger.release("forced")
    for i in (1, 3, 5):
        ledger.release(f"k{i}")
    assert mirror.divergence(ledger) == 0
    assert mirror.host_usage() == {}


def test_mirror_attach_seeds_preexisting_usage():
    ledger = GlobalQuotaLedger()
    ledger.commit("old", _charges("q:root.b", 1000, 42))
    mirror = DeviceUsageMirror(4)
    ledger.attach_mirror(mirror)  # must seed, not start from zero
    assert mirror.divergence(ledger) == 0
    assert mirror.host_usage() == {"q:root.b": {"cpu": 42}}


def test_provably_exceeds_is_conservative():
    ledger = GlobalQuotaLedger()
    mirror = DeviceUsageMirror(1)
    ledger.attach_mirror(mirror)
    ledger.commit("base", _charges("q:root.c", 1000, 900))
    mirror.refresh(0, ledger)
    # 900 + 200 > 1000: provable on confirmed usage alone
    assert mirror.provably_exceeds(
        [("q:root.c", [("cpu", 1000)], [("cpu", 200)])])
    # 900 + 50 fits: NOT provable (the ledger decides with reservations)
    assert not mirror.provably_exceeds(
        [("q:root.c", [("cpu", 1000)], [("cpu", 50)])])
    # unknown tracker: zero confirmed usage, never provable
    assert not mirror.provably_exceeds(
        [("q:root.zzz", [("cpu", 10)], [("cpu", 5)])])


def test_mirror_bit_equal_across_quarantine():
    front, cb = _front(n=3, nodes=6)
    try:
        assert front.usage_mirror is not None
        victim = 1
        apps = _apps_homed(3, victim, "mapp", 2)
        for i, app in enumerate(apps):
            _submit_app(front, app)
            front.update_allocation(AllocationRequest(
                asks=[_ask(app, f"mpod-{i}")]))
        front.flush()
        front.schedule_once()
        assert len(cb.new) == len(apps)
        assert front.quarantine_shard(victim, "manual")
        front.schedule_once()
        assert front.usage_mirror.divergence(front.ledger) == 0
        assert front.ledger.audit() == []
        assert front.obs.get(
            "shard_ledger_mirror_divergence").value() == 0
    finally:
        front.stop()


def test_reserve_many_sequentially_exact():
    a = GlobalQuotaLedger()
    b = GlobalQuotaLedger()
    items = []
    # 5 asks of 300 against a 1000 cap: exactly 3 fit, and the batched
    # path must agree with back-to-back reserve() calls bit-for-bit
    for i in range(5):
        items.append((f"r{i}", _charges("q:root.d", 1000, 300)))
    items.append(("free", []))  # empty charges always succeed
    seq = [a.reserve(k, c) for k, c in items]
    bat = b.reserve_many(items)
    assert bat == seq == [True, True, True, False, False, True]
    assert a.stats()["reservations"] == b.stats()["reservations"]
    assert a.stats()["reserve_held"] == b.stats()["reserve_held"]


# ---------------------------------------------------------------- bind pools
def test_bind_pool_per_key_fifo_ordering():
    pool = ShardedBindPool(n_shards=2, workers_per_shard=4, name="t")
    try:
        order = {k: [] for k in range(4)}
        done = []
        mu = threading.Lock()

        def task(key, seq):
            def run():
                time.sleep(0.001 * (seq % 3))  # jitter to expose races
                with mu:
                    order[key].append(seq)
                    done.append(1)
            return run

        n_each = 20
        for seq in range(n_each):
            for key in range(4):
                assert pool.submit(task(key, seq), key=f"uid-{key}",
                                   shard=key % 2)
        deadline = time.time() + 10
        while len(done) < 4 * n_each and time.time() < deadline:
            time.sleep(0.01)
        assert len(done) == 4 * n_each
        for key in range(4):
            assert order[key] == list(range(n_each)), \
                f"per-key FIFO broken for uid-{key}"
        assert pool.depth(0) == 0 and pool.depth(1) == 0
    finally:
        pool.shutdown()


def test_bind_pool_shutdown_refuses_new_work():
    pool = ShardedBindPool(n_shards=1, workers_per_shard=2, name="t2")
    ran = []
    assert pool.submit(lambda: ran.append(1), key="x")
    deadline = time.time() + 5
    while not ran and time.time() < deadline:
        time.sleep(0.01)
    pool.shutdown()
    assert pool.submit(lambda: ran.append(2), key="x") is False
    assert ran == [1]


def test_bind_pool_metrics_publish_stable_zeros():
    from yunikorn_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    pool = ShardedBindPool(n_shards=2, workers_per_shard=2, name="t3")
    try:
        pool.attach_metrics(reg)
        assert reg.get("bind_pool_depth").value(shard="0") == 0
        assert reg.get("bind_pool_depth").value(shard="1") == 0
        assert reg.get("bind_pool_tasks_total").value(shard="1") == 0
        done = threading.Event()
        pool.submit(done.set, key="k", shard=1)
        assert done.wait(5)
        deadline = time.time() + 5
        while (reg.get("bind_pool_tasks_total").value(shard="1") < 1
               and time.time() < deadline):
            time.sleep(0.01)
        assert reg.get("bind_pool_tasks_total").value(shard="1") == 1
        assert reg.get("bind_pool_tasks_total").value(shard="0") == 0
    finally:
        pool.shutdown()
