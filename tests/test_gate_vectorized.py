"""Differential suite for the array-form admission gate (core/gate.py).

vector_admit must be indistinguishable from legacy_admit — identical admitted
set, identical global order, identical held count — across randomized queue
trees with nested quotas, user/group limits, priority offsets/fences,
pre-loaded accounting, gang asks, and the pipelined gate's seed_admissions /
exclude-keys traces. The randomized cases are seeded (deterministic); the
end-to-end cases run the full CoreScheduler in verify mode (the vectorized
gate runs, the legacy loop re-runs as the oracle, gate_mismatch_total pins
zero) over sequential AND pipelined cycles.
"""
import random

import pytest

from yunikorn_tpu.common.resource import Resource
from yunikorn_tpu.common.si import AllocationAsk, UserGroupInfo
from yunikorn_tpu.core import gate as gate_mod
from yunikorn_tpu.core.gate import GateFallback, legacy_admit, vector_admit
from yunikorn_tpu.core.queues import LimitConfig, QueueConfig, QueueTree

USERS = [
    ("alice", ["dev"]),
    ("bob", ["dev", "ops"]),
    ("carol", []),
    # duplicated group: the legacy loop double-charges the shared group
    # accumulator for this user's admissions — the vector gate's weighted
    # membership must reproduce that exactly
    ("dave", ["ops", "ops"]),
]

CAP = Resource({"cpu": 1000, "memory": 1000, "gpu": 64})


class FakeApp:
    """The three attributes the gate reads off an application."""

    def __init__(self, user, groups, submit_time, queue_name):
        self.user = UserGroupInfo(user=user, groups=list(groups))
        self.submit_time = submit_time
        self.queue_name = queue_name


def _rand_res(rng, lo, hi, gpu_p=0.3):
    out = {}
    for name, p in (("cpu", 0.9), ("memory", 0.8), ("gpu", gpu_p)):
        if rng.random() < p:
            out[name] = rng.randint(lo, hi)
    return Resource(out)


def random_tree(rng) -> QueueTree:
    """Random 1-3 level hierarchy: quotas on ~half the nodes (parents too,
    so sibling leaves share a constrained ancestor), limits on ~a third,
    priority offsets and fences sprinkled in."""

    def mk(name, depth):
        cfg = QueueConfig(name=name)
        if rng.random() < 0.55:
            cfg.max_resource = _rand_res(rng, 8, 60)
        if rng.random() < 0.35:
            cfg.limits = [
                LimitConfig(
                    users=rng.choice([["*"], ["alice"], ["alice", "bob"],
                                      ["dave"], []]),
                    groups=rng.choice([[], ["dev"], ["*"], ["dev", "ops"],
                                       ["ops"]]),
                    max_resources=_rand_res(rng, 4, 40),
                )
                for _ in range(rng.randint(1, 2))
            ]
        if rng.random() < 0.4:
            cfg.properties["priority.offset"] = str(rng.randint(-3, 3))
            if rng.random() < 0.3:
                cfg.properties["priority.policy"] = "fence"
        if depth < 2 and rng.random() < 0.5:
            cfg.parent = True
            for i in range(rng.randint(1, 3)):
                cfg.children.append(mk(f"{name}c{i}", depth + 1))
        return cfg

    root = QueueConfig(name="root", parent=True)
    for i in range(rng.randint(1, 4)):
        root.children.append(mk(f"q{i}", 1))
    return QueueTree(root)


def preload_accounting(rng, tree):
    """Pre-existing allocations: committed usage the budgets subtract."""
    for leaf in tree.leaves():
        if rng.random() < 0.6:
            r = _rand_res(rng, 0, 20)
            leaf.add_allocated(r)
            user, groups = rng.choice(USERS)
            leaf.add_user_allocated(user, r, groups)


def random_trace(rng, tree, n_asks=None):
    leaves = [q.full_name for q in tree.leaves()]
    by_queue = {}
    apps = {}
    for i in range(n_asks if n_asks is not None else rng.randint(1, 120)):
        qname = rng.choice(leaves)
        user, groups = rng.choice(USERS)
        app = apps.get((qname, user))
        if app is None:
            app = apps[(qname, user)] = FakeApp(
                user, groups, round(rng.random() * 100, 3), qname)
        gang = rng.random() < 0.15
        ask = AllocationAsk(
            f"ask-{i}", f"app-{qname}-{user}",
            _rand_res(rng, 0, 12),
            priority=rng.choice([0, 0, 0, 1, 5, -2]),
            placeholder=gang and rng.random() < 0.5,
            task_group_name="tg" if gang else "",
            seq=i)
        by_queue.setdefault(qname, []).append((app, ask))
    return by_queue


def meta_for(tree, by_queue, cap=CAP):
    meta = {}
    for qname in by_queue:
        leaf = tree.resolve(qname, create=False)
        meta[qname] = (leaf,
                       leaf.dominant_share(cap) if leaf else 0.0,
                       leaf.priority_adjustment() if leaf else 0)
    return meta


def random_seeds(rng, tree):
    leaves = [q.full_name for q in tree.leaves()]
    seeds = []
    for _ in range(rng.randint(0, 8)):
        user, groups = rng.choice(USERS)
        seeds.append((rng.choice(leaves), _rand_res(rng, 0, 10),
                      user, tuple(groups)))
    return seeds


def both_paths(tree, by_queue, seeds=None):
    """Run vector then legacy on copies of the same trace; neither path may
    mutate tree state (asserted implicitly by running them back to back)."""
    v_adm, v_held, stats = vector_admit(
        {q: list(v) for q, v in by_queue.items()},
        meta_for(tree, by_queue), tree, seeds)
    l_adm, l_held = legacy_admit(
        {q: list(v) for q, v in by_queue.items()},
        meta_for(tree, by_queue), tree, seeds)
    return (v_adm, v_held, stats), (l_adm, l_held)


def assert_equivalent(tree, by_queue, seeds=None):
    (v_adm, v_held, _), (l_adm, l_held) = both_paths(tree, by_queue, seeds)
    assert [a.allocation_key for a in v_adm] == \
        [a.allocation_key for a in l_adm]
    assert v_held == l_held


# --------------------------------------------------------------- randomized
def test_randomized_trees_differential():
    """60 seeded random (tree, accounting, trace) scenarios — quota chains,
    nested limits, fences, gang asks — vector == legacy exactly."""
    for seed in range(60):
        rng = random.Random(seed)
        tree = random_tree(rng)
        preload_accounting(rng, tree)
        by_queue = random_trace(rng, tree)
        assert_equivalent(tree, by_queue)


def test_randomized_with_seed_admissions():
    """The pipelined gate's in-flight charge (seed_admissions) reproduced:
    vector budget charging == legacy cycle_extra pre-population."""
    for seed in range(40):
        rng = random.Random(1000 + seed)
        tree = random_tree(rng)
        preload_accounting(rng, tree)
        by_queue = random_trace(rng, tree)
        assert_equivalent(tree, by_queue, seeds=random_seeds(rng, tree))


def test_randomized_order_is_total():
    """The admitted order must be the legacy order even with heavy priority
    ties (many asks per queue, few distinct priorities/submit times)."""
    for seed in range(20):
        rng = random.Random(2000 + seed)
        tree = random_tree(rng)
        leaves = [q.full_name for q in tree.leaves()]
        apps = {q: FakeApp("alice", ["dev"], 1.0, q) for q in leaves}
        by_queue = {}
        for i in range(150):
            q = rng.choice(leaves)
            ask = AllocationAsk(f"t-{i}", "app", Resource({"cpu": 1}),
                                priority=rng.choice([0, 1]), seq=i)
            by_queue.setdefault(q, []).append((apps[q], ask))
        assert_equivalent(tree, by_queue)


# ------------------------------------------------------------- edge shapes
def _flat_tree(max_resource=None, limits=(), props=None):
    leaf = QueueConfig(name="q", max_resource=max_resource,
                       limits=list(limits), properties=dict(props or {}))
    root = QueueConfig(name="root", parent=True, children=[leaf])
    return QueueTree(root)


def test_no_constraints_pure_ranking():
    tree = _flat_tree()
    app = FakeApp("alice", ["dev"], 5.0, "root.q")
    by_queue = {"root.q": [
        (app, AllocationAsk(f"a{i}", "app", Resource({"cpu": 1}),
                            priority=i % 3, seq=i))
        for i in range(10)]}
    (v_adm, v_held, stats), (l_adm, l_held) = both_paths(tree, by_queue)
    assert [a.allocation_key for a in v_adm] == \
        [a.allocation_key for a in l_adm]
    assert (v_held, l_held) == (0, 0)
    assert stats["trackers"] == 0          # never built a budget matrix


def test_queue_already_over_quota_holds_everything():
    """allocated > max before the cycle: every ask held, even asks that do
    not request the violating resource (within_limit checks the TOTAL)."""
    tree = _flat_tree(max_resource=Resource({"cpu": 10}))
    leaf = tree.resolve("root.q", create=False)
    leaf.add_allocated(Resource({"cpu": 12}))
    app = FakeApp("alice", [], 1.0, "root.q")
    by_queue = {"root.q": [
        (app, AllocationAsk("a0", "app", Resource({"memory": 5}), seq=0)),
        (app, AllocationAsk("a1", "app", Resource({"cpu": 1}), seq=1)),
    ]}
    (v_adm, v_held, _), (l_adm, l_held) = both_paths(tree, by_queue)
    assert v_adm == [] and l_adm == []
    assert v_held == l_held == 2


def test_partial_fit_boundary():
    """Exactly-at-quota admissions: the boundary ask admits, the next holds,
    and a smaller later ask can still slot in (the legacy loop's behavior)."""
    tree = _flat_tree(max_resource=Resource({"cpu": 10}))
    app = FakeApp("alice", [], 1.0, "root.q")
    by_queue = {"root.q": [
        (app, AllocationAsk("a0", "app", Resource({"cpu": 6}), seq=0)),
        (app, AllocationAsk("a1", "app", Resource({"cpu": 5}), seq=1)),  # held
        (app, AllocationAsk("a2", "app", Resource({"cpu": 4}), seq=2)),
        (app, AllocationAsk("a3", "app", Resource({"cpu": 1}), seq=3)),  # held
    ]}
    (v_adm, v_held, _), (l_adm, l_held) = both_paths(tree, by_queue)
    assert [a.allocation_key for a in v_adm] == ["a0", "a2"] == \
        [a.allocation_key for a in l_adm]
    assert v_held == l_held == 2


def test_group_limit_shared_across_users():
    """A group limit caps the group's AGGREGATE in-cycle usage across
    different users (and sibling leaves under a limited parent)."""
    lim = LimitConfig(groups=["dev"], max_resources=Resource({"cpu": 8}))
    child_a = QueueConfig(name="a")
    child_b = QueueConfig(name="b")
    parent = QueueConfig(name="p", parent=True, limits=[lim],
                         children=[child_a, child_b])
    tree = QueueTree(QueueConfig(name="root", parent=True, children=[parent]))
    alice = FakeApp("alice", ["dev"], 1.0, "root.p.a")
    bob = FakeApp("bob", ["dev"], 2.0, "root.p.b")
    by_queue = {
        "root.p.a": [(alice, AllocationAsk("a0", "app",
                                           Resource({"cpu": 5}), seq=0))],
        "root.p.b": [(bob, AllocationAsk("b0", "app",
                                         Resource({"cpu": 5}), seq=1))],
    }
    (v_adm, v_held, _), (l_adm, l_held) = both_paths(tree, by_queue)
    assert [a.allocation_key for a in v_adm] == \
        [a.allocation_key for a in l_adm]
    assert v_held == l_held == 1           # second leaf blows the shared cap


def test_duplicate_group_double_charges():
    """dave's ["ops", "ops"] double-charges the ops aggregate per admission
    (legacy record_cycle_admission folds once per list entry); the check
    itself uses the request once. The weighted vector scan must agree."""
    lim = LimitConfig(groups=["ops"], max_resources=Resource({"cpu": 10}))
    tree = _flat_tree(limits=[lim])
    dave = FakeApp("dave", ["ops", "ops"], 1.0, "root.q")
    by_queue = {"root.q": [
        (dave, AllocationAsk(f"d{i}", "app", Resource({"cpu": 3}), seq=i))
        for i in range(4)]}
    (v_adm, v_held, _), (l_adm, l_held) = both_paths(tree, by_queue)
    assert [a.allocation_key for a in v_adm] == \
        [a.allocation_key for a in l_adm]
    # 3 charged as 6: d0 passes (check 0+3<=10), d1 passes (6+3<=10),
    # d2 holds (12+3>10), d3 holds
    assert v_held == l_held == 2


def test_priority_fence_ordering():
    props = {"priority.offset": "5", "priority.policy": "fence"}
    tree = _flat_tree(props=props)
    app = FakeApp("alice", [], 1.0, "root.q")
    by_queue = {"root.q": [
        (app, AllocationAsk("lo", "app", Resource({"cpu": 1}),
                            priority=0, seq=0)),
        (app, AllocationAsk("hi", "app", Resource({"cpu": 1}),
                            priority=3, seq=1)),
    ]}
    (v_adm, _, _), (l_adm, _) = both_paths(tree, by_queue)
    assert [a.allocation_key for a in v_adm] == ["hi", "lo"] == \
        [a.allocation_key for a in l_adm]


def test_oversized_quantity_raises_gatefallback():
    tree = _flat_tree(max_resource=Resource({"cpu": 10}))
    app = FakeApp("alice", [], 1.0, "root.q")
    by_queue = {"root.q": [
        (app, AllocationAsk("big", "app",
                            Resource({"cpu": 1 << 50}), seq=0))]}
    with pytest.raises(GateFallback):
        vector_admit(by_queue, meta_for(tree, by_queue), tree)
    # the legacy loop (the production fallback) still decides it
    l_adm, l_held = legacy_admit(by_queue, meta_for(tree, by_queue), tree)
    assert l_adm == [] and l_held == 1


def test_batch_ceiling_raises_gatefallback(monkeypatch):
    monkeypatch.setattr(gate_mod, "_MAX_ASKS", 4)
    tree = _flat_tree(max_resource=Resource({"cpu": 100}))
    app = FakeApp("alice", [], 1.0, "root.q")
    by_queue = {"root.q": [
        (app, AllocationAsk(f"a{i}", "app", Resource({"cpu": 1}), seq=i))
        for i in range(5)]}
    with pytest.raises(GateFallback):
        vector_admit(by_queue, meta_for(tree, by_queue), tree)


def test_weighted_charge_ceiling_raises_gatefallback(monkeypatch):
    """Duplicated-group charge weights multiply the cumulative-sum bound:
    w_max * n must fit the same ceiling as n, else the exact int64 scan
    could trip an unconstrained column or wrap — fall back, never wrap."""
    monkeypatch.setattr(gate_mod, "_MAX_ASKS", 4)
    lim = LimitConfig(groups=["ops"], max_resources=Resource({"cpu": 100}))
    tree = _flat_tree(limits=[lim])
    dave = FakeApp("dave", ["ops", "ops"], 1.0, "root.q")
    by_queue = {"root.q": [
        (dave, AllocationAsk(f"d{i}", "app", Resource({"cpu": 1}), seq=i))
        for i in range(3)]}              # n=3 fits the batch cap; 2x3 doesn't
    with pytest.raises(GateFallback):
        vector_admit(by_queue, meta_for(tree, by_queue), tree)
    # under the real ceiling the weighted trace still matches legacy
    monkeypatch.setattr(gate_mod, "_MAX_ASKS", 1 << 18)
    assert_equivalent(tree, by_queue)


def test_pass_cap_falls_through_to_exact_finish(monkeypatch):
    """With the vectorized pass budget forced to 1, the per-ask exact finish
    must complete the cycle with the identical result."""
    monkeypatch.setattr(gate_mod, "_MAX_PASSES", 1)
    for seed in range(10):
        rng = random.Random(3000 + seed)
        tree = random_tree(rng)
        preload_accounting(rng, tree)
        by_queue = random_trace(rng, tree)
        assert_equivalent(tree, by_queue)


# ------------------------------------------------------------- end to end
def _e2e_core(queues_yaml, gate_verify=True, **core_kwargs):
    # this file pins the HOST vectorized gate; the device tier has its own
    # e2e verify suite (tests/test_gate_device.py)
    core_kwargs.setdefault("gate_device", False)
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.client.synthetic import make_kwok_nodes
    from yunikorn_tpu.common.si import (
        NodeAction, NodeInfo, NodeRequest, RegisterResourceManagerRequest)
    from yunikorn_tpu.core.scheduler import CoreScheduler, SolverOptions

    class NullCallback:
        def __getattr__(self, name):
            return lambda *a, **k: None

    cache = SchedulerCache()
    core = CoreScheduler(
        cache,
        solver_options=SolverOptions(gate_verify=gate_verify, **core_kwargs))
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="gate-e2e", policy_group="queues",
                                       config=queues_yaml),
        NullCallback())
    nodes = make_kwok_nodes(16)
    for n in nodes:
        cache.update_node(n)
    core.update_node(NodeRequest(nodes=[
        NodeInfo(node_id=n.name, action=NodeAction.CREATE) for n in nodes]))
    return cache, core


E2E_YAML = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: qa
            resources:
              max: {vcore: 8, memory: 16Gi}
            limits:
              - users: ["ua"]
                maxresources: {vcore: 4}
          - name: qb
            properties:
              priority.offset: "2"
"""


def _submit(core, app_id, queue, user, pods):
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import (
        AddApplicationRequest, AllocationRequest, ApplicationRequest,
        UserGroupInfo)

    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id=app_id, queue_name=queue,
        user=UserGroupInfo(user=user, groups=["g"]))]))
    core.update_allocation(AllocationRequest(asks=[
        AllocationAsk(p.uid, app_id, get_pod_resource(p), pod=p)
        for p in pods]))


def test_e2e_verify_mode_sequential():
    """Full scheduler, verify mode on: the vectorized gate runs every cycle,
    the legacy oracle re-runs after it, and the mismatch counter pins 0
    across quota-held, limit-held and plain cycles."""
    from yunikorn_tpu.common.objects import make_pod

    cache, core = _e2e_core(E2E_YAML)
    _submit(core, "appa", "root.qa", "ua",
            [make_pod(f"pa-{i}", cpu_milli=1000, memory="512Mi")
             for i in range(12)])
    _submit(core, "appb", "root.qb", "ub",
            [make_pod(f"pb-{i}", cpu_milli=500, memory="256Mi")
             for i in range(8)])
    for _ in range(3):
        core.schedule_once()
    assert core.obs.get("gate_mismatch_total").value() == 0
    assert core.obs.get("gate_path_total").value(path="vector") >= 3
    # the qa quota (4 vcore user limit under an 8 vcore max) held some asks
    assert core.obs.get("unschedulable_total").value(reason="quota_held") > 0


def test_e2e_verify_mode_pipelined():
    """Pipelined ticks: the overlap gate runs with exclude_keys +
    seed_admissions; the oracle re-runs with the same overlays — no drift."""
    from yunikorn_tpu.common.objects import make_pod

    cache, core = _e2e_core(E2E_YAML)
    for w in range(3):
        _submit(core, f"appw{w}", "root.qa", "ua",
                [make_pod(f"pw{w}-{i}", cpu_milli=700, memory="128Mi")
                 for i in range(5)])
        core._pipeline_tick()
    for _ in range(4):
        core._pipeline_tick()
    assert core._pipeline_inflight is None
    assert core.obs.get("gate_mismatch_total").value() == 0
    assert core.obs.get("gate_path_total").value(path="vector") >= 3


def test_e2e_gate_disabled_runs_legacy():
    from yunikorn_tpu.common.objects import make_pod

    cache, core = _e2e_core(E2E_YAML, gate_verify=False, gate_vector=False)
    _submit(core, "appa", "root.qa", "ua",
            [make_pod("pl-0", cpu_milli=500, memory="128Mi")])
    core.schedule_once()
    assert core.obs.get("gate_path_total").value(path="legacy") >= 1
    assert core.obs.get("gate_path_total").value(path="vector") == 0


def test_e2e_gang_trace_verify():
    """Gang apps (placeholders + real asks) through verify-mode cycles."""
    from yunikorn_tpu.common.objects import make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import (
        AddApplicationRequest, AllocationRequest, ApplicationRequest,
        TaskGroup, UserGroupInfo)

    cache, core = _e2e_core(E2E_YAML)
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="gang", queue_name="root.qa",
        user=UserGroupInfo(user="ua"),
        task_groups=[TaskGroup(name="tg", min_member=3,
                               min_resource={"cpu": "500m"})])]))
    phs = [make_pod(f"ph-{i}", cpu_milli=500) for i in range(3)]
    core.update_allocation(AllocationRequest(asks=[
        AllocationAsk(p.uid, "gang", get_pod_resource(p), placeholder=True,
                      task_group_name="tg", pod=p) for p in phs]))
    core.schedule_once()
    real = [make_pod(f"rm-{i}", cpu_milli=500) for i in range(3)]
    core.update_allocation(AllocationRequest(asks=[
        AllocationAsk(p.uid, "gang", get_pod_resource(p),
                      task_group_name="tg", pod=p) for p in real]))
    core.schedule_once()
    assert core.obs.get("gate_mismatch_total").value() == 0


# ------------------------------------------------- ask-level extraction cache
def test_extract_cache_rederives_only_changed():
    """Churn contract (the round-11 ROADMAP follow-up): with an
    AskExtractCache threaded through, a second extraction over a mostly
    unchanged pending set re-derives ONLY the new asks — and produces a
    GateProblem bit-identical to the cache-less extraction."""
    import numpy as np

    rng = random.Random(42)
    tree = random_tree(rng)
    by_queue = random_trace(rng, tree, n_asks=100)
    cache = gate_mod.AskExtractCache()

    p_cold = gate_mod.extract_problem(by_queue, meta_for(tree, by_queue),
                                      tree, cache=cache)
    assert cache.derived == p_cold.n and cache.hits == 0

    # churn: 10 new asks join, everything else unchanged
    leaves = [q.full_name for q in tree.leaves()]
    app = FakeApp("alice", ["dev"], 55.0, leaves[0])
    for i in range(10):
        by_queue.setdefault(leaves[0], []).append((app, AllocationAsk(
            f"churn-{i}", "app-churn", _rand_res(rng, 0, 12), seq=1000 + i)))
    p_warm = gate_mod.extract_problem(by_queue, meta_for(tree, by_queue),
                                      tree, cache=cache)
    assert cache.derived == 10, (cache.derived, cache.hits)
    assert cache.hits == p_warm.n - 10

    # equivalence: cached extraction == cache-less extraction, bit for bit
    p_ref = gate_mod.extract_problem(by_queue, meta_for(tree, by_queue), tree)
    assert [a.allocation_key for a in p_warm.asks_ord] == \
        [a.allocation_key for a in p_ref.asks_ord]
    for field in ("status0", "Rm", "B", "mem_tr", "mem_pos", "mem_w"):
        assert np.array_equal(getattr(p_warm, field), getattr(p_ref, field)), \
            field

    # a REPLACED ask object (same key, new ask) must re-derive
    qn, entries = next((q, v) for q, v in by_queue.items() if v)
    old_app, old_ask = entries[0]
    entries[0] = (old_app, AllocationAsk(
        old_ask.allocation_key, old_ask.application_id,
        Resource({"cpu": 1}), seq=old_ask.seq))
    gate_mod.extract_problem(by_queue, meta_for(tree, by_queue), tree,
                             cache=cache)
    assert cache.derived == 1

    # IN-PLACE mutations on the SAME ask object (update_allocation restamps
    # seq; priority/resource could be swapped) must also re-derive
    churn_app, churn_ask = by_queue[leaves[0]][-1]
    churn_ask.seq += 5000
    gate_mod.extract_problem(by_queue, meta_for(tree, by_queue), tree,
                             cache=cache)
    assert cache.derived == 1
    churn_ask.resource = Resource({"memory": 2})
    p_mut = gate_mod.extract_problem(by_queue, meta_for(tree, by_queue),
                                     tree, cache=cache)
    assert cache.derived == 1
    p_mut_ref = gate_mod.extract_problem(by_queue, meta_for(tree, by_queue),
                                         tree)
    assert np.array_equal(p_mut.Rm, p_mut_ref.Rm)


def test_extract_cache_admission_parity():
    """Randomized parity: cached extraction feeds host_scan the same
    decisions the cache-less path makes, across churn waves."""
    for seed in range(6):
        rng = random.Random(seed)
        tree = random_tree(rng)
        by_queue = random_trace(rng, tree)
        cache = gate_mod.AskExtractCache()
        for wave in range(3):
            p_c = gate_mod.extract_problem(
                by_queue, meta_for(tree, by_queue), tree, cache=cache)
            adm_c, held_c, _ = gate_mod.host_scan(p_c)
            p_r = gate_mod.extract_problem(
                by_queue, meta_for(tree, by_queue), tree)
            adm_r, held_r, _ = gate_mod.host_scan(p_r)
            assert held_c == held_r
            assert [a.allocation_key for a in adm_c] == \
                [a.allocation_key for a in adm_r]
            # next wave: drop some, add some
            for q in list(by_queue):
                by_queue[q] = [e for e in by_queue[q] if rng.random() < 0.7]
                if not by_queue[q]:
                    del by_queue[q]
            extra = random_trace(rng, tree, n_asks=rng.randint(1, 30))
            for q, v in extra.items():
                by_queue.setdefault(q, []).extend(v)
            if not by_queue:
                break
