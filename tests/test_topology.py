"""Topology-aware placement (round 15): the ICI-domain model, the
contention/gang score steering, the topology-off identity contract, the
mesh-aligned pack partitioner, and the preemption domain ordering.
"""
import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder
from yunikorn_tpu.topology.model import (
    LABEL_ICI_DOMAIN,
    LABEL_RACK,
    LABEL_SLICE,
    domain_free_units,
    fragmentation,
    normalize_topology_labels,
    parse_topology_labels,
)
from yunikorn_tpu.topology.score import (
    build_topo_args,
    plan_gang_domains,
    preempt_node_order,
)


def topo_labels(dom: int, sl: int = 0) -> dict:
    return {LABEL_SLICE: f"slice-{sl}", LABEL_RACK: f"rack-{sl}-{dom // 2}",
            LABEL_ICI_DOMAIN: f"ici-{dom}"}


def make_cluster(n_nodes=32, domains=4, cpu_milli=8000, mem=8 * 2**30,
                 labeled=True):
    """Cache + encoder over a regular topology grid."""
    cache = SchedulerCache()
    per = n_nodes // domains
    for i in range(n_nodes):
        labels = topo_labels(i // per) if labeled else {}
        cache.update_node(make_node(f"n{i}", cpu_milli=cpu_milli, memory=mem,
                                    labels=labels))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return cache, enc


# ---------------------------------------------------------------- model
def test_parse_and_normalize_labels():
    sl, rack, ici = parse_topology_labels(topo_labels(3))
    assert sl == "slice-0" and rack == "rack-0-1"
    assert ici == ("slice-0", "ici-3")
    # domain names are slice-scoped: same ici label, different slice
    assert parse_topology_labels(topo_labels(3, sl=1))[2] == ("slice-1", "ici-3")
    # ici without slice still yields a (scoped) domain
    assert parse_topology_labels({LABEL_ICI_DOMAIN: "x"})[2] == ("", "x")
    assert parse_topology_labels({}) == (None, None, None)
    # provider aliases fold into the canonical set; canonical wins
    lbl = normalize_topology_labels(
        {"cloud.google.com/gke-tpu-slice": "s7",
         "topology.kubernetes.io/rack": "r1"})
    assert lbl[LABEL_SLICE] == "s7" and lbl[LABEL_RACK] == "r1"
    both = normalize_topology_labels(
        {"cloud.google.com/gke-tpu-slice": "alias", LABEL_SLICE: "canon"})
    assert both[LABEL_SLICE] == "canon"
    plain = {"zone": "z1"}
    assert normalize_topology_labels(plain) is plain  # allocation-free path


def test_encoder_interns_topology_coordinates():
    cache, enc = make_cluster(n_nodes=8, domains=2)
    na = enc.nodes
    assert na.has_topology and na.num_ici_domains == 2
    for i in range(8):
        idx = na.index_of(f"n{i}")
        assert na.topo[idx, 2] == i // 4          # dense domain ids
        assert na.topo[idx, 0] == 0               # one slice
    # unlabeled node stays -1 everywhere
    cache.update_node(make_node("plain", cpu_milli=1000, memory=2**30))
    enc.sync_nodes()
    assert (na.topo[na.index_of("plain")] == -1).all()
    # removal clears the row so a reused slot can't leak a domain
    cache.remove_node("n0")
    enc.sync_nodes()
    assert (na.topo[0 if na.index_of("n1") != 0 else 1] != -2).all()  # sanity
    removed_row = [i for i in range(na.capacity)
                   if na._idx_to_name.get(i) is None and i < 9]
    assert all((na.topo[i] == -1).all() for i in removed_row)


def test_device_mirror_carries_topo_field():
    _cache, enc = make_cluster(n_nodes=8, domains=2)
    arrays = enc.device_arrays()
    assert "topo" in arrays
    np.testing.assert_array_equal(np.asarray(arrays["topo"]), enc.nodes.topo)
    # incremental: a node-object change re-uploads topo with the full field
    # set; pod churn does not touch it (update_free_row marks free_i/ports)
    dev = enc.device
    enc.device_arrays()
    assert dev.last_refresh == "clean"


def test_domain_units_and_fragmentation():
    node_dom = np.array([0, 0, 1, -1])
    free = np.array([[4, 0], [4, 0], [8, 0], [100, 0]], np.int64)
    cap = np.array([[8, 0], [8, 0], [8, 0], [100, 0]], np.int64)
    free_d, cap_d = domain_free_units(node_dom, free, cap, 2)
    assert free_d.shape == (2,)
    assert cap_d[0] == 2 * cap_d[1] // 2 * 2  # two nodes vs one
    # unlabeled node's capacity never lands in any domain
    assert free_d.sum() < 100 * 1024
    assert fragmentation(np.array([10, 0])) == 0.0
    assert fragmentation(np.array([5, 5])) == 0.5
    assert fragmentation(np.array([], np.int64)) == 0.0


# ---------------------------------------------------------------- planner
def test_plan_gang_domains_prefers_fit_presence_and_empty():
    free_d = np.array([100, 300, 300], np.int64)
    cap_d = np.array([400, 400, 300], np.int64)
    # gang A (demand 200): domain 0 does not fit; 1 is busier than 2;
    # domain 2 is co-tenant-free -> picks 2
    plan = plan_gang_domains(["A"], {"A": 200}, {}, free_d, cap_d)
    assert plan["A"] == 2
    # presence beats emptiness among fitting domains
    pres = {"B": np.array([0, 5, 0], np.int64)}
    plan = plan_gang_domains(["B"], {"B": 200}, pres, free_d, cap_d)
    assert plan["B"] == 1
    # capacity charging: two 200-demand gangs cannot stampede domain 2
    plan = plan_gang_domains(["A", "C"], {"A": 200, "C": 200}, {},
                             free_d, cap_d)
    assert plan["A"] == 2 and plan["C"] == 1
    assert plan_gang_domains(["A"], {"A": 1}, {}, np.array([], np.int64),
                             np.array([], np.int64)) == {}


def _asks(pods, app="app"):
    return [AllocationAsk(p.uid, app, get_pod_resource(p), pod=p)
            for p in pods]


def test_build_topo_args_plans_gang_targets():
    _cache, enc = make_cluster(n_nodes=32, domains=4)
    pods = [make_pod(f"g{i}", cpu_milli=1000, memory=2**27) for i in range(6)]
    pods += [make_pod("solo", cpu_milli=500, memory=2**26)]
    asks = _asks(pods[:6], app="gang") + _asks(pods[6:], app="solo")
    batch = enc.build_batch(asks)
    ta = build_topo_args(asks, batch, enc.nodes, app_rows={})
    assert ta is not None
    assert ta.stats["domains"] == 4 and ta.stats["gangs"] == 1
    # gang rows share one planned target domain; the solo ask (and the
    # padding rows) stay unsteered
    prefs = set(ta.pref_pod[:6].tolist())
    assert len(prefs) == 1 and prefs.pop() >= 0
    assert ta.pref_pod[6] == -1
    assert (ta.pref_pod[batch.num_pods:] == -1).all()
    assert ta.node_dom.shape[0] == enc.nodes.capacity
    # no labels -> no args (the auto-off identity path)
    _c2, enc2 = make_cluster(n_nodes=8, domains=2, labeled=False)
    b2 = enc2.build_batch(asks)
    assert build_topo_args(asks, b2, enc2.nodes, app_rows={}) is None


# ---------------------------------------------------------------- solve
def test_gang_lands_in_one_ici_domain():
    from yunikorn_tpu.ops.assign import solve_batch

    _cache, enc = make_cluster(n_nodes=32, domains=4)
    na = enc.nodes
    pods = [make_pod(f"g{i}", cpu_milli=2000, memory=2**28) for i in range(8)]
    asks = _asks(pods, app="gang")
    batch = enc.build_batch(asks)
    batch.topo = build_topo_args(asks, batch, enc.nodes, app_rows={})
    assert batch.topo is not None
    res = solve_batch(batch, na)
    assigned = np.asarray(res.assigned)[:8]
    assert (assigned >= 0).all()
    doms = {int(na.topo[i, 2]) for i in assigned}
    assert len(doms) == 1, f"gang spread across domains {doms}"
    assert doms == {int(batch.topo.pref_pod[0])}


def test_empty_domain_bonus_steers_equal_scores():
    from yunikorn_tpu.ops.assign import solve_batch

    cache = SchedulerCache()
    # two domains, equal-fill nodes; domain 0 is made busy by loading its
    # OTHER node, so its free node carries a contention penalty
    cache.update_node(make_node("a0", cpu_milli=4000, memory=4 * 2**30,
                                labels=topo_labels(0)))
    cache.update_node(make_node("a1", cpu_milli=4000, memory=4 * 2**30,
                                labels=topo_labels(0)))
    cache.update_node(make_node("b0", cpu_milli=4000, memory=4 * 2**30,
                                labels=topo_labels(1)))
    filler = make_pod("filler", cpu_milli=3000, memory=2**28,
                      node_name="a1")
    cache.update_pod(filler)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pod = make_pod("p", cpu_milli=1000, memory=2**27)
    asks = _asks([pod], app="solo")
    batch = enc.build_batch(asks)
    base = solve_batch(batch, enc.nodes)
    batch.topo = build_topo_args(asks, batch, enc.nodes,
                                 app_rows={"solo": []})
    res = solve_batch(batch, enc.nodes)
    na = enc.nodes
    topo_dom = int(na.topo[int(np.asarray(res.assigned)[0]), 2])
    assert topo_dom == 1  # the co-tenant-free domain
    # sanity: the un-steered program exists and places somewhere valid
    assert int(np.asarray(base.assigned)[0]) >= 0


def test_topology_off_is_bit_identical_to_unlabeled():
    """The differential oracle: a labeled cluster with solver.topology=off
    places EXACTLY like the same cluster with no topology labels at all
    (topology labels reach the solver only through the topo args)."""
    from yunikorn_tpu.ops.assign import solve_batch

    rng = np.random.default_rng(7)
    sizes = rng.integers(200, 2000, size=40).tolist()

    def run(labeled):
        _cache, enc = make_cluster(n_nodes=16, domains=4, labeled=labeled)
        pods = [make_pod(f"p{i}", cpu_milli=int(s), memory=2**26)
                for i, s in enumerate(sizes)]
        asks = _asks(pods)
        batch = enc.build_batch(asks)
        assert getattr(batch, "topo", None) is None  # off: never attached
        res = solve_batch(batch, enc.nodes)
        return np.asarray(res.assigned)[: batch.num_pods]

    np.testing.assert_array_equal(run(True), run(False))


# ------------------------------------------------------------- pack/topo
def test_pack_topo_partitioner_parts_are_domain_aligned():
    from yunikorn_tpu.ops import pack_solve as pack_mod
    from yunikorn_tpu.ops.assign import prepare_solve_args

    _cache, enc = make_cluster(n_nodes=64, domains=8, cpu_milli=16000,
                               mem=16 * 2**30)
    pods = [make_pod(f"p{i}", cpu_milli=400 + 100 * (i % 5), memory=2**26)
            for i in range(256)]
    asks = _asks(pods)
    batch = enc.build_batch(asks)
    batch.topo = build_topo_args(asks, batch, enc.nodes, app_rows={})
    res = pack_mod.pack_solve_batch(batch, enc.nodes, seed=3)
    assert res.partitioner == "topo"
    assigned = np.asarray(res.assigned)[: batch.num_pods]
    assert (assigned >= 0).all()
    assert bool(np.asarray(res.feasible))
    # determinism: same inputs, same seed -> identical plan
    res2 = pack_mod.pack_solve_batch(batch, enc.nodes, seed=3)
    np.testing.assert_array_equal(assigned,
                                  np.asarray(res2.assigned)[: batch.num_pods])


def test_pick_parts_floors_at_shard_count():
    from yunikorn_tpu.ops.pack_solve import pick_parts, shape_supported

    assert pick_parts(256, 64) == 1
    assert pick_parts(256, 64, n_shards=8) == 8
    assert pick_parts(256, 64, n_shards=8) % 8 == 0
    assert shape_supported(256, 64, n_shards=8)
    # shapes that cannot split into whole parts per shard are refused
    assert not shape_supported(3, 64, n_shards=8)
    # pick_parts doubles in powers of two, so a non-power-of-two shard
    # count can never be honored — the same shape stays packable
    # single-device (the core's "mesh-shape" vs "shape" skip distinction)
    assert not shape_supported(256, 64, n_shards=6)
    assert shape_supported(256, 64)


def test_pack_sharded_parity_vs_single_shard():
    """The PACK_SHARDED_SUPPORTED contract: the mesh-sharded pack solve is
    placement-identical to the single-device solve of the SAME program
    (same mesh-aligned partition, same seed, same args)."""
    import jax

    from yunikorn_tpu.aot import runtime as aot_rt
    from yunikorn_tpu.ops import pack_solve as pack_mod
    from yunikorn_tpu.ops.assign import prepare_solve_args
    from yunikorn_tpu.parallel import mesh as mesh_mod

    assert mesh_mod.PACK_SHARDED_SUPPORTED
    _cache, enc = make_cluster(n_nodes=64, domains=8, cpu_milli=16000,
                               mem=16 * 2**30)
    pods = [make_pod(f"p{i}", cpu_milli=400 + 100 * (i % 5), memory=2**26)
            for i in range(256)]
    # a couple of gangs so the topo args are non-trivial
    asks = (_asks(pods[:120], app="gang-a") + _asks(pods[120:240], app="gang-b")
            + _asks(pods[240:], app="solo"))
    batch = enc.build_batch(asks)
    batch.topo = build_topo_args(asks, batch, enc.nodes, app_rows={})
    mesh = mesh_mod.make_mesh()
    n_dev = mesh.devices.size
    sharded = mesh_mod.pack_solve_sharded(batch, enc.nodes, mesh, seed=11)

    np_args, static_kwargs = prepare_solve_args(batch, enc.nodes)
    import jax.numpy as jnp

    single = pack_mod.pack_solve(
        *jax.tree_util.tree_map(jnp.asarray, np_args), jnp.int32(11),
        n_parts=sharded.n_parts, partitioner="topo", n_shards=n_dev,
        score_cols=static_kwargs["score_cols"])
    a_sharded = np.asarray(sharded.assigned)[: batch.num_pods]
    a_single = np.asarray(single[0])[: batch.num_pods]
    np.testing.assert_array_equal(a_sharded, a_single)
    np.testing.assert_array_equal(np.asarray(sharded.free_after),
                                  np.asarray(single[1]))


# ------------------------------------------------------------- preempt
def test_preempt_node_order_prefers_open_domains():
    cache, enc = make_cluster(n_nodes=8, domains=2, cpu_milli=4000)
    # load domain 0 heavily: its nodes hold pods, domain 1 stays free
    for i in range(4):
        p = make_pod(f"busy{i}", cpu_milli=3000, memory=2**27,
                     node_name=f"n{i}")
        cache.update_pod(p)
    enc.sync_nodes()
    names = [f"n{i}" for i in range(8)]
    ordered = preempt_node_order(names, enc.nodes)
    # domain 1 (most free capacity) first, stable order within each domain
    assert ordered[:4] == ["n4", "n5", "n6", "n7"]
    assert ordered[4:] == ["n0", "n1", "n2", "n3"]
    # unlabeled clusters pass through untouched
    _c2, enc2 = make_cluster(n_nodes=4, domains=2, labeled=False)
    assert preempt_node_order(["n1", "n0"], enc2.nodes) == ["n1", "n0"]


# ------------------------------------------------------------------ conf
def test_solver_topology_tri_state():
    from yunikorn_tpu.conf.schedulerconf import (CM_SOLVER_TOPOLOGY,
                                                 parse_config_map)
    from yunikorn_tpu.core.scheduler import SolverOptions

    conf = parse_config_map({CM_SOLVER_TOPOLOGY: "false"})
    assert SolverOptions.from_conf(conf).topology is False
    conf = parse_config_map({CM_SOLVER_TOPOLOGY: "true"})
    assert SolverOptions.from_conf(conf).topology is True
    conf = parse_config_map({})
    assert SolverOptions.from_conf(conf).topology is None
    with pytest.raises(ValueError):
        parse_config_map({CM_SOLVER_TOPOLOGY: "bogus"})


# -------------------------------------------------------------------- e2e
def _register(core):
    from yunikorn_tpu.common.si import RegisterResourceManagerRequest

    class CB:
        def __init__(self):
            self.allocs = {}

        def update_allocation(self, response):
            for a in response.new:
                self.allocs[a.allocation_key] = a.node_id

        def update_application(self, r): pass
        def update_node(self, r): pass
        def predicates(self, a): return None
        def preemption_predicates(self, a): return None
        def send_event(self, e): pass
        def update_container_scheduling_state(self, r): pass
        def get_state_dump(self): return "{}"

    cb = CB()
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="t", policy_group="queues"), cb)
    return cb


def _submit(core, cache, asks_spec):
    from yunikorn_tpu.common.si import (AddApplicationRequest,
                                        AllocationRequest, ApplicationRequest,
                                        NodeAction, NodeInfo, NodeRequest,
                                        UserGroupInfo)

    infos = [NodeInfo(node_id=n, action=NodeAction.CREATE)
             for n in cache.node_names()]
    core.update_node(NodeRequest(nodes=infos))
    apps = sorted({app for _p, app in asks_spec})
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id=a, queue_name="root.default",
                              user=UserGroupInfo(user="u")) for a in apps]))
    asks = [AllocationAsk(p.uid, app, get_pod_resource(p), pod=p)
            for p, app in asks_spec]
    core.update_allocation(AllocationRequest(asks=asks))


def test_core_cycle_places_gang_in_one_domain_and_counts():
    from yunikorn_tpu.core.scheduler import CoreScheduler, SolverOptions

    cache, enc = make_cluster(n_nodes=32, domains=4)
    core = CoreScheduler(cache, solver_options=SolverOptions())
    core.encoder = enc  # reuse the synced encoder's interning
    cb = _register(core)
    spec = [(make_pod(f"g{i}", cpu_milli=2000, memory=2**28), "gangapp")
            for i in range(8)]
    _submit(core, cache, spec)
    n = core.schedule_once()
    assert n == 8
    na = core.encoder.nodes
    doms = {int(na.topo[na.index_of(node), 2]) for node in cb.allocs.values()}
    assert len(doms) == 1
    ms = core.metrics
    assert ms.get("topology_gangs_total", 0) >= 1
    assert ms.get("topology_cross_domain_gangs_total", 0) == 0
    entry = core.metrics["last_cycle"]["default"]
    assert "topo_fragmentation" in entry
    assert entry.get("topo_cycle_gangs", 0) >= 1
    # the fold must actually have ENGAGED (batch.topo built, plan stats
    # recorded) — a silently-failing fold still commits plausible-looking
    # gang counts on an uncontended cluster (caught by the e2e drive)
    assert entry.get("topo_gangs", 0) >= 1
    assert entry.get("topo_domains", 0) == 4


def test_core_topology_off_never_attaches():
    from yunikorn_tpu.core.scheduler import CoreScheduler, SolverOptions

    cache, _enc = make_cluster(n_nodes=8, domains=2)
    core = CoreScheduler(cache,
                         solver_options=SolverOptions(topology=False))
    _register(core)
    spec = [(make_pod(f"p{i}", cpu_milli=500, memory=2**26), "app")
            for i in range(4)]
    _submit(core, cache, spec)
    assert core.schedule_once() == 4
    assert not core._topology_active
    assert core.metrics.get("topology_gangs_total", 0) == 0
