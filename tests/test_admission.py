"""Admission controller tests (reference admission_controller_test.go, 2073
lines — same scenarios: schedulerName patch, label injection, user-info
auth, namespace filtering, immutability, workload templates, conf validation,
PKI rotation; plus the live HTTP webhook).
"""
import json

import pytest

from yunikorn_tpu.admission.admission_controller import (
    AdmissionController,
    decode_patch,
)
from yunikorn_tpu.admission.caches import NamespaceCache, PriorityClassCache
from yunikorn_tpu.admission.conf import AdmissionConf, parse_admission_conf
from yunikorn_tpu.admission.pki import HAVE_CRYPTOGRAPHY
from yunikorn_tpu.common import constants

# The PKI/webhook tier needs the `cryptography` package, which the baked
# build environment does not ship (and cannot install); admission/pki.py
# gates its import so everything else here runs regardless. These six tests
# skip-with-reason instead of failing collection — documented in TESTING.md,
# so the tier-1 dots count carries no known noise into SLO gating.
requires_cryptography = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY,
    reason="cryptography package not installed (environmental): the "
           "PKI/webhook tests exercise real X.509 generation/rotation")


def make_review(pod=None, kind="Pod", operation="CREATE", namespace="default",
                username="alice", groups=None, old=None, uid="uid-1"):
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "uid": uid,
            "kind": {"kind": kind},
            "namespace": namespace,
            "operation": operation,
            "userInfo": {"username": username, "groups": groups or ["dev"]},
            "object": pod or {},
            "oldObject": old or {},
        },
    }


def simple_pod(name="p1", labels=None, annotations=None, scheduler=""):
    meta = {"name": name, "uid": f"uid-{name}"}
    if labels is not None:
        meta["labels"] = labels
    if annotations is not None:
        meta["annotations"] = annotations
    spec = {}
    if scheduler:
        spec["schedulerName"] = scheduler
    return {"metadata": meta, "spec": spec}


@pytest.fixture
def ac():
    return AdmissionController(AdmissionConf())


def patch_ops(result):
    return {(p["op"], p["path"]) for p in decode_patch(result)}


def test_scheduler_name_patched(ac):
    result = ac.mutate(make_review(simple_pod()))
    patch = decode_patch(result)
    sn = [p for p in patch if p["path"] == "/spec/schedulerName"]
    assert sn and sn[0]["value"] == "yunikorn"
    assert result["response"]["allowed"]


def test_app_id_and_queue_labels_added(ac):
    result = ac.mutate(make_review(simple_pod()))
    labels_patch = [p for p in decode_patch(result) if p["path"] == "/metadata/labels"]
    assert labels_patch
    labels = labels_patch[0]["value"]
    assert labels[constants.LABEL_APPLICATION_ID].startswith("yunikorn-default-")
    assert labels[constants.LABEL_QUEUE_NAME] == "root.default"


def test_existing_app_id_kept(ac):
    pod = simple_pod(labels={"applicationId": "my-app", "queue": "root.q"})
    result = ac.mutate(make_review(pod))
    labels_patch = [p for p in decode_patch(result) if p["path"] == "/metadata/labels"]
    assert not labels_patch  # nothing to add


def test_user_info_injected(ac):
    result = ac.mutate(make_review(simple_pod(), username="alice", groups=["dev", "ops"]))
    ann_patch = [p for p in decode_patch(result) if p["path"] == "/metadata/annotations"]
    assert ann_patch
    info = json.loads(ann_patch[0]["value"][constants.ANNOTATION_USER_INFO])
    assert info["user"] == "alice" and info["groups"] == ["dev", "ops"]


def test_system_user_trusted_no_injection(ac):
    result = ac.mutate(make_review(
        simple_pod(), username="system:serviceaccount:kube-system:deployment-controller"))
    ann_patch = [p for p in decode_patch(result) if p["path"] == "/metadata/annotations"]
    assert not ann_patch


def test_bypass_auth_no_injection():
    conf = parse_admission_conf({"admissionController.accessControl.bypassAuth": "true"})
    ac = AdmissionController(conf)
    result = ac.mutate(make_review(simple_pod()))
    ann_patch = [p for p in decode_patch(result) if p["path"] == "/metadata/annotations"]
    assert not ann_patch


def test_bypass_namespace_not_processed(ac):
    result = ac.mutate(make_review(simple_pod(), namespace="kube-system"))
    # no schedulerName patch for bypassed namespaces
    assert ("add", "/spec/schedulerName") not in patch_ops(result)


def test_process_namespaces_regex():
    conf = parse_admission_conf(
        {"admissionController.filtering.processNamespaces": "^spark-,^batch$"})
    ac = AdmissionController(conf)
    assert ("add", "/spec/schedulerName") in patch_ops(
        ac.mutate(make_review(simple_pod(), namespace="spark-jobs")))
    assert ("add", "/spec/schedulerName") not in patch_ops(
        ac.mutate(make_review(simple_pod(), namespace="other")))


def test_namespace_annotation_overrides_regex():
    ac = AdmissionController(AdmissionConf())
    ac.namespaces.namespace_updated(
        "opt-out", {constants.ANNOTATION_ENABLE_YUNIKORN: "false"})
    assert ("add", "/spec/schedulerName") not in patch_ops(
        ac.mutate(make_review(simple_pod(), namespace="opt-out")))
    ac.namespaces.namespace_updated(
        "kube-system", {constants.ANNOTATION_ENABLE_YUNIKORN: "true"})
    assert ("add", "/spec/schedulerName") in patch_ops(
        ac.mutate(make_review(simple_pod(), namespace="kube-system")))


def test_yunikorn_own_pods_skipped(ac):
    pod = simple_pod(labels={"app": "yunikorn"})
    assert decode_patch(ac.mutate(make_review(pod))) == []


def test_ignore_application_annotation(ac):
    pod = simple_pod(annotations={constants.ANNOTATION_IGNORE_APPLICATION: "true"})
    assert ("add", "/spec/schedulerName") not in patch_ops(ac.mutate(make_review(pod)))


def test_user_info_immutable_on_update(ac):
    old = simple_pod(annotations={constants.ANNOTATION_USER_INFO: '{"user":"a"}'})
    new = simple_pod(annotations={constants.ANNOTATION_USER_INFO: '{"user":"b"}'})
    result = ac.mutate(make_review(new, operation="UPDATE", old=old))
    assert result["response"]["allowed"] is False
    result = ac.mutate(make_review(old, operation="UPDATE", old=old))
    assert result["response"]["allowed"] is True


def test_preemption_annotation_from_priority_class(ac):
    ac.priority_classes.priority_class_updated(
        "no-preempt", {constants.ANNOTATION_ALLOW_PREEMPTION: "false"})
    pod = simple_pod()
    pod["spec"]["priorityClassName"] = "no-preempt"
    result = ac.mutate(make_review(pod))
    ann_patch = [p for p in decode_patch(result) if p["path"] == "/metadata/annotations"]
    merged = {}
    for p in ann_patch:
        merged.update(p["value"])
    assert merged.get(constants.ANNOTATION_ALLOW_PREEMPTION) == "false"


def test_cronjob_template_path(ac):
    cj = {
        "metadata": {"name": "c1"},
        "spec": {"jobTemplate": {"spec": {"template": {"metadata": {}, "spec": {}}}}},
    }
    result = ac.mutate(make_review(cj, kind="CronJob", username="bob"))
    patch = decode_patch(result)
    assert patch[0]["path"] == "/spec/jobTemplate/spec/template/metadata/annotations"


def test_validate_conf():
    calls = []

    def validate(yaml_text):
        calls.append(yaml_text)
        return ("bad" not in yaml_text), "invalid queue config" if "bad" in yaml_text else ""

    ac = AdmissionController(AdmissionConf(), validate_conf_fn=validate)
    cm = {"metadata": {"name": "yunikorn-configs"}, "data": {"queues.yaml": "partitions: []"}}
    result = ac.validate_conf(make_review(cm, kind="ConfigMap"))
    assert result["response"]["allowed"]
    cm_bad = {"metadata": {"name": "yunikorn-configs"}, "data": {"queues.yaml": "bad yaml"}}
    result = ac.validate_conf(make_review(cm_bad, kind="ConfigMap"))
    assert not result["response"]["allowed"]
    # unrelated configmaps are always allowed, validator not called
    n = len(calls)
    other = {"metadata": {"name": "some-cm"}, "data": {}}
    assert ac.validate_conf(make_review(other, kind="ConfigMap"))["response"]["allowed"]
    assert len(calls) == n


# ---------------------------------------------------------------------------
# PKI + live webhook server
# ---------------------------------------------------------------------------

@requires_cryptography
def test_pki_generation_and_rotation():
    from yunikorn_tpu.admission.pki import CACollection, generate_server_cert

    cas = CACollection()
    server, bundle = cas.server_credentials(["localhost"])
    assert b"BEGIN CERTIFICATE" in server.cert_pem
    assert bundle.count(b"BEGIN CERTIFICATE") == 2
    assert server.seconds_until_expiry() > 300 * 24 * 3600
    assert cas.rotate_if_needed() is False  # fresh CAs, no rotation


@requires_cryptography
def test_webhook_server_http_roundtrip():
    import urllib.request

    from yunikorn_tpu.admission.webhook import WebhookServer

    ac = AdmissionController(AdmissionConf())
    server = WebhookServer(ac, port=0)
    port = server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/mutate",
            data=json.dumps(make_review(simple_pod())).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["response"]["allowed"]
        assert body["response"]["patchType"] == "JSONPatch"
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=5) as resp:
            assert json.loads(resp.read())["status"] == "ok"
    finally:
        server.stop()


@requires_cryptography
def test_webhook_manager_manifests():
    from yunikorn_tpu.admission.webhook import WebhookManager

    mgr = WebhookManager(AdmissionConf())
    m = mgr.mutating_webhook_config()
    assert m["webhooks"][0]["clientConfig"]["caBundle"].count("BEGIN CERTIFICATE") == 2
    v = mgr.validating_webhook_config()
    assert v["webhooks"][0]["rules"][0]["resources"] == ["configmaps"]
    assert mgr.wait_for_certificate_expiration_seconds() > 0


# ---------------------------------------------------------------------------
# Round-2: namespace regex matrix (reference admission_controller_test.go's
# processNamespaces/bypassNamespaces/labelNamespaces/noLabelNamespaces grid)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("process,bypass,ns,expected", [
    # no lists: everything processed except built-in bypass defaults
    ("", "", "default", True),
    ("", "", "kube-system", False),          # default bypassNamespaces
    ("", "", "kube-public", True),           # reference default bypasses only kube-system
    # processNamespaces whitelist
    ("^spark-,^batch$", "", "spark-jobs", True),
    ("^spark-,^batch$", "", "batch", True),
    ("^spark-,^batch$", "", "other", False),
    ("^spark-,^batch$", "", "notbatch", False),
    # bypass wins over process
    ("^spark-", "^spark-skip", "spark-skip-1", False),
    ("^spark-", "^spark-skip", "spark-ok", True),
    # regex is a search, not fullmatch (reference semantics)
    ("ml", "", "team-ml-jobs", True),
    # invalid regex entries are dropped, valid ones still apply
    ("[invalid,^good$", "", "good", True),
    ("[invalid,^good$", "", "bad", False),
])
def test_namespace_processing_matrix(process, bypass, ns, expected):
    flat = {"admissionController.filtering.processNamespaces": process}
    if bypass:
        flat["admissionController.filtering.bypassNamespaces"] = bypass
    conf = parse_admission_conf(flat)
    assert conf.should_process_namespace(ns) is expected


@pytest.mark.parametrize("label,nolabel,ns,expected", [
    ("", "", "anyns", True),
    ("^spark", "", "spark-1", True),
    ("^spark", "", "other", False),
    ("", "^secret", "secret-ns", False),
    ("", "^secret", "open-ns", True),
    # noLabel wins over label
    ("^spark", "^spark-hidden", "spark-hidden-2", False),
])
def test_namespace_labeling_matrix(label, nolabel, ns, expected):
    flat = {}
    if label:
        flat["admissionController.filtering.labelNamespaces"] = label
    if nolabel:
        flat["admissionController.filtering.noLabelNamespaces"] = nolabel
    conf = parse_admission_conf(flat)
    assert conf.should_label_namespace(ns) is expected


def test_conf_hot_reload_via_holder():
    """Standalone-binary conf hot reload (reference am_conf.go:85-394): the
    controller reads the LIVE conf through the holder."""
    from yunikorn_tpu.admission.conf import AdmissionConfHolder

    holder = AdmissionConfHolder()
    ac = AdmissionController(holder.get(), conf_holder=holder)
    pod = simple_pod()
    res = ac.mutate(make_review(pod, namespace="skipme"))
    assert ("add", "/spec/schedulerName") in patch_ops(res)  # processed
    holder.update({"admissionController.filtering.bypassNamespaces": "^skipme$"})
    res = ac.mutate(make_review(pod, namespace="skipme"))
    # hot-reloaded: the schedulerName patch no longer applies (user-info
    # annotation still does — auth is independent of namespace filtering)
    assert ("add", "/spec/schedulerName") not in patch_ops(res)


def test_admission_informer_attachment_feeds_conf_and_caches():
    from yunikorn_tpu.admission.caches import attach_informers
    from yunikorn_tpu.admission.conf import AdmissionConfHolder
    from yunikorn_tpu.client.fake import FakeCluster
    from yunikorn_tpu.common.objects import ConfigMap, Namespace, ObjectMeta, PriorityClass

    cluster = FakeCluster()
    holder = AdmissionConfHolder()
    ns_cache, pc_cache = NamespaceCache(), PriorityClassCache()
    attach_informers(cluster, holder, ns_cache, pc_cache)
    cluster.start()  # informers fan out only after start
    cluster.add_configmap(ConfigMap(
        metadata=ObjectMeta(name="yunikorn-configs", namespace="yunikorn"),
        data={"admissionController.filtering.processNamespaces": "^only$"}))
    assert holder.get().should_process_namespace("only")
    assert not holder.get().should_process_namespace("other")
    cluster.add_namespace(Namespace(metadata=ObjectMeta(
        name="annotated",
        annotations={constants.ANNOTATION_ENABLE_YUNIKORN: "true"})))
    assert ns_cache.enable_yunikorn("annotated") == 1
    cluster.add_priority_class(PriorityClass(
        metadata=ObjectMeta(name="no-preempt",
                            annotations={constants.ANNOTATION_ALLOW_PREEMPTION: "false"}),
        value=100))
    assert not pc_cache.is_preemption_allowed("no-preempt")


@requires_cryptography
def test_certificate_expiration_loop_rotates():
    import threading
    import time as _time

    from yunikorn_tpu.admission.pki import CACollection
    from yunikorn_tpu.admission.webhook import WebhookManager

    cas = CACollection()
    manager = WebhookManager(AdmissionConf(), cas)
    rotated = []
    stop = threading.Event()
    # make rotation immediately due: the 12-month certs are "within" the
    # rotation window when the window is enormous
    old_window = CACollection.ROTATE_BEFORE_SECONDS
    CACollection.ROTATE_BEFORE_SECONDS = 10 * 365 * 24 * 3600.0
    try:
        manager.run_certificate_expiration_loop(
            stop, on_rotated=lambda m, v: rotated.append((m, v)))
        deadline = _time.time() + 15
        while not rotated and _time.time() < deadline:
            _time.sleep(0.05)
    finally:
        stop.set()
        CACollection.ROTATE_BEFORE_SECONDS = old_window
    assert rotated, "expected a rotation + webhook re-registration"
    m, v = rotated[0]
    assert m["webhooks"][0]["clientConfig"]["caBundle"]  # fresh bundle rendered


@pytest.mark.parametrize("kind", ["Deployment", "DaemonSet", "StatefulSet",
                                  "ReplicaSet", "Job"])
def test_all_workload_kinds_get_user_info(ac, kind):
    """processWorkload covers all 6 kinds (reference :218-281); CronJob's
    nested template path is covered separately."""
    wl = {
        "metadata": {"name": f"{kind.lower()}-1"},
        "spec": {"template": {"metadata": {}, "spec": {}}},
    }
    result = ac.mutate(make_review(wl, kind=kind, username="carol"))
    patch = decode_patch(result)
    assert patch and patch[0]["path"] == "/spec/template/metadata/annotations"
    info = json.loads(patch[0]["value"][constants.ANNOTATION_USER_INFO])
    assert info["user"] == "carol"


@requires_cryptography
def test_webhook_install_and_repatch_against_api():
    """InstallWebhooks through the HTTP client: create when absent, no-op
    when current, PUT (preserving resourceVersion) after a caBundle rotation
    (reference webhook_manager.go:185-379)."""
    import ssl

    from tests.fake_apiserver import FakeAPIServer
    from yunikorn_tpu.admission.webhook import WebhookManager
    from yunikorn_tpu.client.kube import KubeConfig, RealKubeClient

    server = FakeAPIServer()
    port = server.start()
    try:
        client = RealKubeClient(
            KubeConfig(f"http://127.0.0.1:{port}", ssl.create_default_context()))
        mgr = WebhookManager(AdmissionConf())
        mgr.install_webhooks(client)
        mut = server.store["mutatingwebhookconfigurations"]
        val = server.store["validatingwebhookconfigurations"]
        assert "yunikorn-admission-controller-cfg" in mut
        assert "yunikorn-admission-controller-cfg" in val
        bundle0 = mut["yunikorn-admission-controller-cfg"][
            "webhooks"][0]["clientConfig"]["caBundle"]
        rv0 = mut["yunikorn-admission-controller-cfg"]["metadata"]["resourceVersion"]

        # idempotent: second install with unchanged desired state writes nothing
        writes_before = [r for r in server.requests if r[0] in ("POST", "PUT")]
        mgr.install_webhooks(client)
        assert [r for r in server.requests
                if r[0] in ("POST", "PUT")] == writes_before

        # rotation drifts the caBundle -> install patches in place (force
        # rotation due by widening the window, as the expiration-loop test does)
        from yunikorn_tpu.admission.pki import CACollection
        old_window = CACollection.ROTATE_BEFORE_SECONDS
        CACollection.ROTATE_BEFORE_SECONDS = 10 * 365 * 24 * 3600.0
        try:
            assert mgr.cas.rotate_if_needed()
        finally:
            CACollection.ROTATE_BEFORE_SECONDS = old_window
        mgr.install_webhooks(client)
        doc = server.store["mutatingwebhookconfigurations"][
            "yunikorn-admission-controller-cfg"]
        assert doc["webhooks"][0]["clientConfig"]["caBundle"] != bundle0
        assert doc["metadata"]["resourceVersion"] != rv0  # replaced, not created
        puts = [p for m, p in server.requests if m == "PUT"]
        assert any("mutatingwebhookconfigurations" in p for p in puts)
    finally:
        server.stop()


@requires_cryptography
def test_webhook_drift_ignores_server_defaults():
    """A stored object that differs only by server-side defaulting
    (matchPolicy/timeoutSeconds on the webhook, scope on rules, port on the
    service ref) is NOT drift; a caBundle change IS."""
    from yunikorn_tpu.admission.webhook import WebhookManager

    mgr = WebhookManager(AdmissionConf())
    desired = mgr.mutating_webhook_config()["webhooks"]
    stored = json.loads(json.dumps(desired))
    w = stored[0]
    w["matchPolicy"] = "Equivalent"          # server defaults
    w["timeoutSeconds"] = 10
    w["namespaceSelector"] = {}
    w["clientConfig"]["service"]["port"] = 443
    for r in w["rules"]:
        r["scope"] = "*"
    assert not WebhookManager._webhooks_drifted(stored, desired)
    w["clientConfig"]["caBundle"] = "ZHJpZnRlZA=="
    assert WebhookManager._webhooks_drifted(stored, desired)


# ---------------------------------------------------------------------------
# External authentication matrix (reference TestExternalAuthentication
# :709-874): pre-set user-info annotations are denied unless the submitter is
# an allowed external identity, and must carry valid user info JSON.
# ---------------------------------------------------------------------------

def ext_ac(**extra):
    flat = {"admissionController.accessControl.externalUsers": "^testExtUser$",
            "admissionController.accessControl.externalGroups": "^extgroup$"}
    flat.update(extra)
    return AdmissionController(parse_admission_conf(flat))


USER_INFO_ANN = constants.ANNOTATION_USER_INFO
VALID_INFO = '{"user": "remoteuser", "groups": ["remotegrp"]}'


@pytest.mark.parametrize("username,groups,info,allowed", [
    # not whitelisted: denied even with valid payload
    ("test", ["dev"], VALID_INFO, False),
    # whitelisted external user: allowed, annotation kept
    ("testExtUser", ["dev"], VALID_INFO, True),
    # whitelisted via group
    ("random", ["extgroup"], VALID_INFO, True),
    # whitelisted but malformed JSON: denied
    ("testExtUser", ["dev"], "xyzxyz", False),
    # whitelisted but wrong shape (groups not a list): denied
    ("testExtUser", ["dev"], '{"user": "u", "groups": "nope"}', False),
])
def test_external_auth_pod_matrix(username, groups, info, allowed):
    ac = ext_ac()
    pod = simple_pod(annotations={USER_INFO_ANN: info})
    result = ac.mutate(make_review(pod, username=username, groups=groups))
    assert result["response"]["allowed"] is allowed
    if allowed:
        # the pre-set identity is preserved verbatim — no overwrite patch
        ann = [p for p in decode_patch(result)
               if p["path"] == "/metadata/annotations"]
        assert not ann


@pytest.mark.parametrize("kind", ["Deployment", "ReplicaSet", "Job"])
def test_external_auth_workload_template(kind):
    """Templates pre-setting the identity follow the same rule as pods."""
    ac = ext_ac()
    wl = {"metadata": {"name": "w1"},
          "spec": {"template": {
              "metadata": {"annotations": {USER_INFO_ANN: VALID_INFO}},
              "spec": {}}}}
    denied = ac.mutate(make_review(wl, kind=kind, username="test"))
    assert denied["response"]["allowed"] is False
    ok = ac.mutate(make_review(wl, kind=kind, username="testExtUser"))
    assert ok["response"]["allowed"] is True
    assert decode_patch(ok) == []               # identity kept as set


def test_replicaset_from_system_user_never_patched():
    """A controller-created ReplicaSet must not be touched even with
    trustControllers=false — patching it respawns a new ReplicaSet forever
    (reference shouldProcessWorkload :330-344)."""
    ac = AdmissionController(parse_admission_conf(
        {"admissionController.accessControl.trustControllers": "false"}))
    rs = {"metadata": {"name": "rs1"},
          "spec": {"template": {"metadata": {}, "spec": {}}}}
    result = ac.mutate(make_review(
        rs, kind="ReplicaSet",
        username="system:serviceaccount:kube-system:deployment-controller"))
    assert result["response"]["allowed"] and decode_patch(result) == []
    # a plain Deployment from the same user IS processed with trust off
    dep = {"metadata": {"name": "d1"},
           "spec": {"template": {"metadata": {}, "spec": {}}}}
    result = ac.mutate(make_review(
        dep, kind="Deployment",
        username="system:serviceaccount:kube-system:deployment-controller"))
    assert decode_patch(result)


# ---------------------------------------------------------------------------
# Label handling breadth (reference TestUpdateLabels :54-253)
# ---------------------------------------------------------------------------

def labels_patch_value(result):
    ps = [p for p in decode_patch(result) if p["path"] == "/metadata/labels"]
    return ps[0]["value"] if ps else None


def test_update_labels_preserves_existing_random_labels(ac):
    pod = simple_pod(labels={"random": "random"})
    value = labels_patch_value(ac.mutate(make_review(pod)))
    assert value["random"] == "random"
    assert value[constants.LABEL_APPLICATION_ID].startswith("yunikorn-")


def test_update_labels_existing_queue_kept(ac):
    pod = simple_pod(labels={"queue": "root.custom"})
    value = labels_patch_value(ac.mutate(make_review(pod)))
    # queue untouched; only the generated appId is added
    assert value["queue"] == "root.custom"
    assert constants.LABEL_QUEUE_NAME not in value or \
        value[constants.LABEL_QUEUE_NAME] == "root.custom"


def test_update_labels_generate_name_pod(ac):
    """Pods from generateName (no metadata.name yet) still get an appId."""
    pod = {"metadata": {"generateName": "burst-", "uid": "u-gen"}, "spec": {}}
    value = labels_patch_value(ac.mutate(make_review(pod)))
    assert value and value[constants.LABEL_APPLICATION_ID]


def test_update_labels_unique_app_ids():
    ac = AdmissionController(parse_admission_conf(
        {"admissionController.filtering.generateUniqueAppId": "true"}))
    pod = simple_pod("uniq")
    value = labels_patch_value(ac.mutate(make_review(pod)))
    app_id = value[constants.LABEL_APPLICATION_ID]
    assert "uid-uniq" in app_id                 # per-pod unique, not shared
    other = labels_patch_value(ac.mutate(make_review(simple_pod("uniq2"))))
    assert other[constants.LABEL_APPLICATION_ID] != app_id


def test_update_labels_empty_namespace_defaults(ac):
    pod = simple_pod()
    result = ac.mutate(make_review(pod, namespace=""))
    value = labels_patch_value(result)
    assert value[constants.LABEL_APPLICATION_ID] == "yunikorn-default-autogen"


# ---------------------------------------------------------------------------
# validate-conf edge cases (reference TestValidateConfigMap* :266-328)
# ---------------------------------------------------------------------------

def test_validate_conf_empty_configmap_allowed():
    ac = AdmissionController(AdmissionConf(), validate_conf_fn=lambda y: (True, ""))
    cm = {"metadata": {"name": "yunikorn-configs"}}
    assert ac.validate_conf(make_review(cm, kind="ConfigMap"))["response"]["allowed"]


def test_validate_conf_missing_object_fails_open():
    ac = AdmissionController(AdmissionConf(), validate_conf_fn=lambda y: (True, ""))
    review = {"request": {"uid": "x", "kind": {"kind": "ConfigMap"},
                          "operation": "UPDATE"}}
    out = ac.validate_conf(review)
    assert out["response"]["uid"] == "x"
    assert out["response"]["allowed"] in (True, False)  # well-formed response


def test_validate_conf_delete_operation_allowed():
    """DELETE of the config map reverts to defaults — always allowed."""
    ac = AdmissionController(AdmissionConf(),
                             validate_conf_fn=lambda y: (False, "never"))
    cm = {"metadata": {"name": "yunikorn-configs"}, "data": {}}
    out = ac.validate_conf(make_review(cm, kind="ConfigMap", operation="DELETE"))
    assert out["response"]["allowed"]


def test_workload_update_with_own_injected_annotation_allowed():
    """Scale/apply on a workload whose template carries the annotation WE
    injected at CREATE must not be denied; changing it still is."""
    ac = ext_ac()
    injected = '{"user": "alice", "groups": ["dev"]}'
    tmpl = {"metadata": {"annotations": {USER_INFO_ANN: injected}}, "spec": {}}
    wl = {"metadata": {"name": "w1"}, "spec": {"template": tmpl,
                                               "replicas": 3}}
    old = {"metadata": {"name": "w1"}, "spec": {"template": tmpl,
                                                "replicas": 1}}
    result = ac.mutate(make_review(wl, kind="Deployment", operation="UPDATE",
                                   old=old, username="alice"))
    assert result["response"]["allowed"] is True
    # but ALTERING the identity on update is still denied for non-externals
    wl2 = {"metadata": {"name": "w1"}, "spec": {"template": {
        "metadata": {"annotations": {USER_INFO_ANN: '{"user":"mallory","groups":[]}'}},
        "spec": {}}}}
    result = ac.mutate(make_review(wl2, kind="Deployment", operation="UPDATE",
                                   old=old, username="alice"))
    assert result["response"]["allowed"] is False
