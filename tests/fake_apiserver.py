"""A minimal in-process Kubernetes API server for adapter tests.

Speaks just enough of the K8s REST protocol to drive client/kube.py the way
kwok drives the reference's client-go layer (deployments/kwok-perf-test):
LIST + streaming WATCH for the informer types, the pods/binding subresource,
object create/update/patch/delete, configmap get. State lives in plain dicts
of K8s JSON documents; bindings mutate spec.nodeName + status.phase and emit
MODIFIED events exactly like a kubelet picking the pod up.

Watch semantics match the real apiserver closely enough to test reflector
edge cases: events are buffered per collection with their resourceVersion,
a watch with `resourceVersion=N` replays buffered events newer than N (so
an event emitted between LIST and WATCH connect is never lost), and
`compact()` discards the buffer so a stale-rv watch gets an ERROR 410
event — driving the client's relist path. `kill_watches()` severs live
watch streams mid-flight for chaos tests.
"""
from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

_COLLECTIONS = {
    "/api/v1/pods": "pods",
    "/api/v1/nodes": "nodes",
    "/api/v1/configmaps": "configmaps",
    "/apis/scheduling.k8s.io/v1/priorityclasses": "priorityclasses",
    "/api/v1/namespaces": "namespaces",
    "/apis/resource.k8s.io/v1beta1/resourceclaims": "resourceclaims",
    "/apis/resource.k8s.io/v1beta1/resourceslices": "resourceslices",
    "/api/v1/persistentvolumeclaims": "persistentvolumeclaims",
    "/api/v1/persistentvolumes": "persistentvolumes",
    "/apis/storage.k8s.io/v1/storageclasses": "storageclasses",
    "/apis/storage.k8s.io/v1/csinodes": "csinodes",
    "/apis/admissionregistration.k8s.io/v1/validatingwebhookconfigurations":
        "validatingwebhookconfigurations",
    "/apis/admissionregistration.k8s.io/v1/mutatingwebhookconfigurations":
        "mutatingwebhookconfigurations",
    "/apis/storage.k8s.io/v1/csidrivers": "csidrivers",
    "/apis/storage.k8s.io/v1/csistoragecapacities": "csistoragecapacities",
    "/apis/storage.k8s.io/v1/volumeattachments": "volumeattachments",
}

# collection name → whether objects are namespaced (for object-path routing)
_NAMESPACED = {
    "pods": True, "configmaps": True, "persistentvolumeclaims": True,
    "resourceclaims": True,
    "nodes": False, "priorityclasses": False, "namespaces": False,
    "resourceslices": False, "persistentvolumes": False,
    "storageclasses": False, "csinodes": False,
    "validatingwebhookconfigurations": False,
    "mutatingwebhookconfigurations": False,
    "csidrivers": False, "volumeattachments": False,
    # CSIStorageCapacity is namespaced upstream; the adapter lists it
    # cluster-wide (all-namespaces), which the path map above serves
    "csistoragecapacities": False,
}

def _coll_of(segment: str) -> Optional[str]:
    """URL path segment → collection name (they coincide for every kind)."""
    return segment if segment in _NAMESPACED else None

_KILL = object()  # sentinel: sever the watch stream abruptly


def _deep_merge(dst: dict, src: dict) -> dict:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        elif v is None:
            dst.pop(k, None)
        else:
            dst[k] = v
    return dst


class FakeAPIServer:
    # how many events each collection buffers for watch replay
    EVENT_LOG_LIMIT = 10000

    def __init__(self):
        self.store: Dict[str, Dict[str, dict]] = {c: {} for c in _COLLECTIONS.values()}
        self._rv = 0
        self._lock = threading.RLock()
        self._watchers: Dict[str, List[queue.Queue]] = {c: [] for c in _COLLECTIONS.values()}
        # per-collection (rv, event) buffer for watch replay
        self._events: Dict[str, List[Tuple[int, dict]]] = {c: [] for c in _COLLECTIONS.values()}
        # rv up to which the event log was compacted (watch below this → 410)
        self._compacted: Dict[str, int] = {c: 0 for c in _COLLECTIONS.values()}
        self.bindings: List[Tuple[str, str]] = []   # (pod name, node name)
        self.requests: List[Tuple[str, str]] = []   # (method, path) audit log
        self._httpd: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------------- lifecycle
    def start(self) -> int:
        server = self

        class Server(ThreadingHTTPServer):
            # default accept backlog (5) resets connections when 32 bind-pool
            # workers + relisting informers hit the server at once — a real
            # apiserver doesn't shed load that way
            request_queue_size = 256

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send_json(self, doc, code=200):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_body(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            # object path forms:
            #   /api/v1/namespaces/{ns}/{kind}/{name}[/{sub}]
            #   /api/v1/{kind}/{name}            (cluster-scoped core)
            #   /apis/{group}/{ver}/{kind}/{name} (cluster-scoped grouped)
            #   /apis/{group}/{ver}/namespaces/{ns}/{kind}/{name} (namespaced grouped)
            def _object_path(self, parts):
                """Returns (coll, ns, name, subresource) or None."""
                if len(parts) >= 6 and parts[0] == "api" and parts[2] == "namespaces":
                    coll = _coll_of(parts[4])
                    if coll and _NAMESPACED.get(coll):
                        sub = parts[6] if len(parts) > 6 else ""
                        return coll, parts[3], parts[5], sub
                if len(parts) >= 7 and parts[0] == "apis" and parts[3] == "namespaces":
                    coll = _coll_of(parts[5])
                    if coll and _NAMESPACED.get(coll):
                        sub = parts[7] if len(parts) > 7 else ""
                        return coll, parts[4], parts[6], sub
                if len(parts) == 4 and parts[0] == "api":
                    coll = _coll_of(parts[2])
                    if coll and not _NAMESPACED.get(coll, True):
                        return coll, "", parts[3], ""
                if len(parts) == 5 and parts[0] == "apis":
                    coll = _coll_of(parts[3])
                    if coll and not _NAMESPACED.get(coll, True):
                        return coll, "", parts[4], ""
                # namespace object itself: /api/v1/namespaces/{name}
                if len(parts) == 4 and parts[:3] == ["api", "v1", "namespaces"]:
                    return "namespaces", "", parts[3], ""
                return None

            def do_GET(self):
                parsed = urlparse(self.path)
                server.requests.append(("GET", parsed.path))
                q = parse_qs(parsed.query)
                coll = _COLLECTIONS.get(parsed.path)
                ns_scope = ""
                if coll is None and parsed.path.count("/namespaces/") == 1:
                    # namespaced LIST: /api/v1/namespaces/ns/configmaps or
                    # /apis/g/v/namespaces/ns/resourceclaims
                    parts = parsed.path.strip("/").split("/")
                    if len(parts) == 5 and parts[0] == "api" and parts[2] == "namespaces":
                        coll = _coll_of(parts[4])
                        ns_scope = parts[3]
                    elif len(parts) == 6 and parts[0] == "apis" and parts[3] == "namespaces":
                        coll = _coll_of(parts[5])
                        ns_scope = parts[4]
                if coll is not None:
                    if q.get("watch", ["false"])[0] == "true":
                        rv = int(q.get("resourceVersion", ["0"])[0] or 0)
                        return self._watch(coll, rv, ns_scope)
                    with server._lock:
                        items = [d for d in server.store[coll].values()
                                 if not ns_scope
                                 or (d.get("metadata") or {}).get("namespace") == ns_scope]
                        rv = str(server._rv)
                    return self._send_json(
                        {"items": items, "metadata": {"resourceVersion": rv}})
                parts = parsed.path.strip("/").split("/")
                obj = self._object_path(parts)
                if obj is not None:
                    coll, ns, name, _ = obj
                    key = f"{ns}/{name}" if ns else name
                    with server._lock:
                        doc = server.store[coll].get(key)
                    if doc is None:
                        return self._send_json({"kind": "Status", "code": 404}, 404)
                    return self._send_json(doc)
                self._send_json({"kind": "Status", "code": 404}, 404)

            def _watch(self, coll, since_rv, ns_scope=""):
                def in_scope(event):
                    if not ns_scope or event is _KILL or event is None:
                        return True
                    meta = (event.get("object") or {}).get("metadata") or {}
                    return meta.get("namespace") == ns_scope

                ch: queue.Queue = queue.Queue()
                with server._lock:
                    if since_rv and since_rv < server._compacted[coll]:
                        # resume window lost: ERROR event carrying 410
                        # (real apiserver "too old resource version")
                        self.send_response(200)
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        self._write_chunk({"type": "ERROR", "object": {
                            "kind": "Status", "code": 410,
                            "reason": "Expired",
                            "message": f"too old resource version: {since_rv}"}})
                        self.wfile.write(b"0\r\n\r\n")
                        return
                    replay = [e for (erv, e) in server._events[coll]
                              if erv > since_rv and in_scope(e)] if since_rv else []
                    server._watchers[coll].append(ch)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    for event in replay:
                        self._write_chunk(event)
                    while True:
                        event = ch.get(timeout=30)
                        if event is None:
                            break
                        if event is _KILL:
                            # abrupt close, no terminal chunk: the client sees
                            # a dead socket mid-stream
                            self.wfile.flush()
                            self.connection.close()
                            return
                        if in_scope(event):
                            self._write_chunk(event)
                except (queue.Empty, BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    with server._lock:
                        if ch in server._watchers[coll]:
                            server._watchers[coll].remove(ch)

            def _write_chunk(self, event):
                line = (json.dumps(event) + "\n").encode()
                self.wfile.write(hex(len(line))[2:].encode() + b"\r\n"
                                 + line + b"\r\n")
                self.wfile.flush()

            def do_POST(self):
                parts = urlparse(self.path).path.strip("/").split("/")
                server.requests.append(("POST", urlparse(self.path).path))
                body = self._read_body()
                # pods/binding subresource
                if len(parts) == 7 and parts[4] == "pods" and parts[6] == "binding":
                    ns, name = parts[3], parts[5]
                    node = (body.get("target") or {}).get("name", "")
                    with server._lock:
                        doc = server.store["pods"].get(f"{ns}/{name}")
                        already = (doc or {}).get("spec", {}).get("nodeName", "")
                    if already:
                        # real apiserver: binding an assigned pod is 409
                        # Conflict — exactly what a retried bind whose first
                        # attempt landed (connection reset after commit) sees
                        return self._send_json(
                            {"kind": "Status", "code": 409, "reason": "Conflict",
                             "message": f"pod {name} is already assigned "
                                        f"to node {already}"}, 409)
                    server.bind_pod(ns, name, node)
                    return self._send_json({"kind": "Status", "status": "Success"}, 201)
                # namespaced collection create — core (/api/v1/namespaces/ns/k)
                # or grouped (/apis/g/v/namespaces/ns/k)
                ns = kind_seg = None
                if len(parts) == 5 and parts[0] == "api" and parts[2] == "namespaces":
                    ns, kind_seg = parts[3], parts[4]
                elif len(parts) == 6 and parts[0] == "apis" and parts[3] == "namespaces":
                    ns, kind_seg = parts[4], parts[5]
                if kind_seg is not None:
                    coll = _coll_of(kind_seg)
                    if coll is not None:
                        body.setdefault("metadata", {}).setdefault("namespace", ns)
                        server.add(coll, body)
                        return self._send_json(body, 201)
                # cluster-scoped collection create
                coll = _COLLECTIONS.get(urlparse(self.path).path)
                if coll is not None:
                    server.add(coll, body)
                    return self._send_json(body, 201)
                self._send_json({"kind": "Status", "code": 404}, 404)

            def do_PUT(self):
                parts = urlparse(self.path).path.strip("/").split("/")
                server.requests.append(("PUT", urlparse(self.path).path))
                body = self._read_body()
                obj = self._object_path(parts)
                if obj is not None:
                    coll, ns, name, _ = obj
                    body.setdefault("metadata", {})["name"] = name
                    if ns:
                        body["metadata"]["namespace"] = ns
                    # a replace must keep the object's identity: client
                    # bodies don't carry the fake's synthetic uid
                    key = f"{ns}/{name}" if ns else name
                    with server._lock:
                        existing = server.store[coll].get(key)
                        if existing is not None:
                            body["metadata"].setdefault(
                                "uid", (existing.get("metadata") or {}).get("uid"))
                    server.add(coll, body)
                    return self._send_json(body)
                self._send_json({"kind": "Status", "code": 404}, 404)

            def do_DELETE(self):
                parts = urlparse(self.path).path.strip("/").split("/")
                server.requests.append(("DELETE", urlparse(self.path).path))
                obj = self._object_path(parts)
                if obj is not None:
                    coll, ns, name, _ = obj
                    server.delete(coll, ns, name)
                    return self._send_json({"kind": "Status", "status": "Success"})
                self._send_json({"kind": "Status", "code": 404}, 404)

            def do_PATCH(self):
                parts = urlparse(self.path).path.strip("/").split("/")
                server.requests.append(("PATCH", urlparse(self.path).path))
                body = self._read_body()
                obj = self._object_path(parts)
                if obj is not None:
                    coll, ns, name, sub = obj
                    key = f"{ns}/{name}" if ns else name
                    with server._lock:
                        doc = server.store[coll].get(key)
                        if doc is not None:
                            # strategic-merge ≈ deep merge for our use
                            _deep_merge(doc, body)
                            server._rv += 1
                            doc["metadata"]["resourceVersion"] = str(server._rv)
                            server._emit(coll, "MODIFIED", doc)
                            return self._send_json(doc)
                    if doc is None and sub == "":
                        return self._send_json({"kind": "Status", "code": 404}, 404)
                self._send_json({"kind": "Status", "status": "Success"})

        self._httpd = Server(("127.0.0.1", 0), Handler)
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self._httpd.server_port

    def stop(self) -> None:
        with self._lock:
            for chans in self._watchers.values():
                for ch in chans:
                    ch.put(None)
        if self._httpd is not None:
            self._httpd.shutdown()

    # ------------------------------------------------------------ chaos hooks
    def kill_watches(self, coll: Optional[str] = None) -> int:
        """Sever live watch streams mid-flight (no clean end). Returns count."""
        n = 0
        with self._lock:
            colls = [coll] if coll else list(self._watchers)
            for c in colls:
                for ch in list(self._watchers[c]):
                    ch.put(_KILL)
                    n += 1
        return n

    def compact(self, coll: Optional[str] = None) -> None:
        """Discard the replay buffer; stale-rv watches now get 410 Gone."""
        with self._lock:
            for c in ([coll] if coll else list(self._events)):
                self._events[c].clear()
                self._compacted[c] = self._rv + 1

    # ----------------------------------------------------------------- state
    def _key(self, doc: dict) -> str:
        m = doc.get("metadata") or {}
        ns = m.get("namespace", "")
        return f"{ns}/{m['name']}" if ns else m["name"]

    def _emit(self, coll: str, etype: str, doc: dict) -> None:
        """Must be called with self._lock held (add/delete/bind do).

        Buffers a deep copy: store docs are mutated in place by bind/PATCH
        while watcher threads serialize queued events, and replay must be a
        faithful history, not the object's current state."""
        event = {"type": etype, "object": json.loads(json.dumps(doc))}
        log = self._events[coll]
        log.append((self._rv, event))
        if len(log) > self.EVENT_LOG_LIMIT:
            drop = len(log) // 2
            # everything at or below the last dropped rv is now unreplayable
            self._compacted[coll] = log[drop - 1][0] + 1
            del log[:drop]
        for ch in list(self._watchers[coll]):
            ch.put(event)

    def add(self, coll: str, doc: dict) -> dict:
        with self._lock:
            self._rv += 1
            meta = doc.setdefault("metadata", {})
            meta.setdefault("uid", f"uid-{coll}-{self._rv}")
            meta["resourceVersion"] = str(self._rv)
            key = self._key(doc)
            existed = key in self.store[coll]
            self.store[coll][key] = doc
            self._emit(coll, "MODIFIED" if existed else "ADDED", doc)
        return doc

    def delete(self, coll: str, namespace: str, name: str) -> None:
        with self._lock:
            key = f"{namespace}/{name}" if namespace else name
            doc = self.store[coll].pop(key, None)
            if doc is not None:
                self._rv += 1
                # the event object must carry the DELETE's rv: reflectors
                # resume from the last event's metadata.resourceVersion, and
                # a stale rv would make the replay buffer re-deliver
                # everything since the object was last written
                doc.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
                self._emit(coll, "DELETED", doc)

    def bind_pod(self, namespace: str, name: str, node: str) -> None:
        """Apply a binding: nodeName + Running, MODIFIED event (kubelet-ish)."""
        with self._lock:
            key = f"{namespace}/{name}"
            doc = self.store["pods"].get(key)
            if doc is None:
                return
            self.bindings.append((name, node))
            doc.setdefault("spec", {})["nodeName"] = node
            doc.setdefault("status", {})["phase"] = "Running"
            self._rv += 1
            doc["metadata"]["resourceVersion"] = str(self._rv)
            self._emit("pods", "MODIFIED", doc)

    # ------------------------------------------------------ document helpers
    @staticmethod
    def topology_labels(index: int, *, nodes_per_domain: int = 16,
                        domains_per_slice: int = 4,
                        racks_per_slice: int = 2) -> dict:
        """Synthesized fleet-topology labels for node `index`: a regular
        (slice, rack, ICI-domain) grid in the canonical topology.yunikorn.io
        label vocabulary (topology/model.py). Deterministic in the index, so
        seeded traces get a stable topology and the replay fingerprint can
        pin domain-level counts."""
        dom = index // max(nodes_per_domain, 1)
        sl = dom // max(domains_per_slice, 1)
        rack = (dom // max(domains_per_slice // max(racks_per_slice, 1), 1)
                % max(racks_per_slice, 1))
        return {
            "topology.yunikorn.io/slice": f"slice-{sl}",
            "topology.yunikorn.io/rack": f"rack-{sl}-{rack}",
            "topology.yunikorn.io/ici-domain": f"ici-{dom % domains_per_slice}",
        }

    def add_node_doc(self, name: str, cpu: str = "8", memory: str = "16Gi",
                     pods: int = 110, labels: Optional[dict] = None,
                     topology_index: Optional[int] = None,
                     nodes_per_domain: int = 16) -> dict:
        lbl = dict(labels or {})
        if topology_index is not None:
            lbl.update(self.topology_labels(
                topology_index, nodes_per_domain=nodes_per_domain))
        return self.add("nodes", {
            "metadata": {"name": name, "labels": lbl},
            "spec": {},
            "status": {"allocatable": {"cpu": cpu, "memory": memory, "pods": str(pods)},
                       "capacity": {"cpu": cpu, "memory": memory, "pods": str(pods)}},
        })

    def add_pod_doc(self, name: str, namespace: str = "default",
                    app_id: str = "app-1", cpu: str = "500m",
                    memory: str = "128Mi", volumes: Optional[list] = None) -> dict:
        doc = {
            "metadata": {"name": name, "namespace": namespace,
                         "labels": {"applicationId": app_id},
                         "creationTimestamp": "2026-01-01T00:00:00Z"},
            "spec": {"schedulerName": "yunikorn",
                     "containers": [{"name": "sleep",
                                     "resources": {"requests": {"cpu": cpu,
                                                                "memory": memory}}}]},
            "status": {"phase": "Pending"},
        }
        if volumes:
            doc["spec"]["volumes"] = volumes
        return self.add("pods", doc)

    def add_pvc_doc(self, name: str, namespace: str = "default",
                    storage_class: str = "standard", storage: str = "1Gi",
                    access_modes: Optional[list] = None,
                    volume_name: str = "", phase: str = "Pending") -> dict:
        return self.add("persistentvolumeclaims", {
            "metadata": {"name": name, "namespace": namespace},
            "spec": {"storageClassName": storage_class,
                     "accessModes": list(access_modes or ["ReadWriteOnce"]),
                     "volumeName": volume_name,
                     "resources": {"requests": {"storage": storage}}},
            "status": {"phase": phase},
        })

    def add_pv_doc(self, name: str, storage_class: str = "standard",
                   storage: str = "1Gi", access_modes: Optional[list] = None,
                   claim_ref: Optional[dict] = None,
                   node_affinity_hosts: Optional[list] = None,
                   phase: str = "Available") -> dict:
        spec = {"storageClassName": storage_class, "capacity": {"storage": storage},
                "accessModes": list(access_modes or ["ReadWriteOnce"])}
        if claim_ref:
            spec["claimRef"] = claim_ref
        if node_affinity_hosts:
            spec["nodeAffinity"] = {"required": {"nodeSelectorTerms": [
                {"matchExpressions": [{"key": "kubernetes.io/hostname",
                                       "operator": "In",
                                       "values": list(node_affinity_hosts)}]}]}}
        return self.add("persistentvolumes", {
            "metadata": {"name": name}, "spec": spec, "status": {"phase": phase}})

    def add_storage_class_doc(self, name: str, binding_mode: str = "Immediate",
                              provisioner: str = "kubernetes.io/no-provisioner") -> dict:
        return self.add("storageclasses", {
            "metadata": {"name": name},
            "provisioner": provisioner,
            "volumeBindingMode": binding_mode,
        })

    def add_csinode_doc(self, name: str, drivers: Optional[list] = None) -> dict:
        return self.add("csinodes", {
            "metadata": {"name": name},
            "spec": {"drivers": [
                {"name": d, "nodeID": name, "allocatable": {"count": 8}}
                for d in (drivers or [])
            ]},
        })
