"""A minimal in-process Kubernetes API server for adapter tests.

Speaks just enough of the K8s REST protocol to drive client/kube.py the way
kwok drives the reference's client-go layer (deployments/kwok-perf-test):
LIST + streaming WATCH for the informer types, the pods/binding subresource,
pod create/delete, configmap get. State lives in plain dicts of K8s JSON
documents; bindings mutate spec.nodeName + status.phase and emit MODIFIED
events exactly like a kubelet picking the pod up.
"""
from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

_COLLECTIONS = {
    "/api/v1/pods": "pods",
    "/api/v1/nodes": "nodes",
    "/api/v1/configmaps": "configmaps",
    "/apis/scheduling.k8s.io/v1/priorityclasses": "priorityclasses",
    "/api/v1/namespaces": "namespaces",
    "/apis/resource.k8s.io/v1beta1/resourceclaims": "resourceclaims",
    "/apis/resource.k8s.io/v1beta1/resourceslices": "resourceslices",
}


class FakeAPIServer:
    def __init__(self):
        self.store: Dict[str, Dict[str, dict]] = {c: {} for c in _COLLECTIONS.values()}
        self._rv = 0
        self._lock = threading.RLock()
        self._watchers: Dict[str, List[queue.Queue]] = {c: [] for c in _COLLECTIONS.values()}
        self.bindings: List[Tuple[str, str]] = []   # (pod name, node name)
        self._httpd: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------------- lifecycle
    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send_json(self, doc, code=200):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_body(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                parsed = urlparse(self.path)
                q = parse_qs(parsed.query)
                coll = _COLLECTIONS.get(parsed.path)
                if coll is not None:
                    if q.get("watch", ["false"])[0] == "true":
                        return self._watch(coll)
                    with server._lock:
                        items = list(server.store[coll].values())
                        rv = str(server._rv)
                    return self._send_json(
                        {"items": items, "metadata": {"resourceVersion": rv}})
                # GET one configmap: /api/v1/namespaces/{ns}/configmaps/{name}
                parts = parsed.path.strip("/").split("/")
                if (len(parts) == 6 and parts[:2] == ["api", "v1"]
                        and parts[2] == "namespaces" and parts[4] == "configmaps"):
                    key = f"{parts[3]}/{parts[5]}"
                    with server._lock:
                        doc = server.store["configmaps"].get(key)
                    if doc is None:
                        return self._send_json({"kind": "Status", "code": 404}, 404)
                    return self._send_json(doc)
                self._send_json({"kind": "Status", "code": 404}, 404)

            def _watch(self, coll):
                ch: queue.Queue = queue.Queue()
                with server._lock:
                    server._watchers[coll].append(ch)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    while True:
                        event = ch.get(timeout=30)
                        if event is None:
                            break
                        line = (json.dumps(event) + "\n").encode()
                        self.wfile.write(hex(len(line))[2:].encode() + b"\r\n"
                                         + line + b"\r\n")
                        self.wfile.flush()
                except (queue.Empty, BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    with server._lock:
                        if ch in server._watchers[coll]:
                            server._watchers[coll].remove(ch)

            def do_POST(self):
                parts = urlparse(self.path).path.strip("/").split("/")
                body = self._read_body()
                # pods/binding subresource
                if len(parts) == 7 and parts[4] == "pods" and parts[6] == "binding":
                    ns, name = parts[3], parts[5]
                    node = (body.get("target") or {}).get("name", "")
                    server.bind_pod(ns, name, node)
                    return self._send_json({"kind": "Status", "status": "Success"}, 201)
                if len(parts) == 5 and parts[4] == "pods":
                    server.add("pods", body)
                    return self._send_json(body, 201)
                self._send_json({"kind": "Status", "code": 404}, 404)

            def do_DELETE(self):
                parts = urlparse(self.path).path.strip("/").split("/")
                if len(parts) == 6 and parts[4] == "pods":
                    ns, name = parts[3], parts[5]
                    server.delete("pods", ns, name)
                    return self._send_json({"kind": "Status", "status": "Success"})
                self._send_json({"kind": "Status", "code": 404}, 404)

            def do_PATCH(self):
                self._read_body()
                self._send_json({"kind": "Status", "status": "Success"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self._httpd.server_port

    def stop(self) -> None:
        with self._lock:
            for chans in self._watchers.values():
                for ch in chans:
                    ch.put(None)
        if self._httpd is not None:
            self._httpd.shutdown()

    # ----------------------------------------------------------------- state
    def _key(self, doc: dict) -> str:
        m = doc.get("metadata") or {}
        ns = m.get("namespace", "")
        return f"{ns}/{m['name']}" if ns else m["name"]

    def _emit(self, coll: str, etype: str, doc: dict) -> None:
        for ch in list(self._watchers[coll]):
            ch.put({"type": etype, "object": doc})

    def add(self, coll: str, doc: dict) -> dict:
        with self._lock:
            self._rv += 1
            meta = doc.setdefault("metadata", {})
            meta.setdefault("uid", f"uid-{coll}-{self._rv}")
            meta["resourceVersion"] = str(self._rv)
            key = self._key(doc)
            existed = key in self.store[coll]
            self.store[coll][key] = doc
            self._emit(coll, "MODIFIED" if existed else "ADDED", doc)
        return doc

    def delete(self, coll: str, namespace: str, name: str) -> None:
        with self._lock:
            key = f"{namespace}/{name}" if namespace else name
            doc = self.store[coll].pop(key, None)
            if doc is not None:
                self._rv += 1
                self._emit(coll, "DELETED", doc)

    def bind_pod(self, namespace: str, name: str, node: str) -> None:
        """Apply a binding: nodeName + Running, MODIFIED event (kubelet-ish)."""
        with self._lock:
            key = f"{namespace}/{name}"
            doc = self.store["pods"].get(key)
            if doc is None:
                return
            self.bindings.append((name, node))
            doc.setdefault("spec", {})["nodeName"] = node
            doc.setdefault("status", {})["phase"] = "Running"
            self._rv += 1
            doc["metadata"]["resourceVersion"] = str(self._rv)
            self._emit("pods", "MODIFIED", doc)

    # ------------------------------------------------------ document helpers
    def add_node_doc(self, name: str, cpu: str = "8", memory: str = "16Gi",
                     pods: int = 110, labels: Optional[dict] = None) -> dict:
        return self.add("nodes", {
            "metadata": {"name": name, "labels": dict(labels or {})},
            "spec": {},
            "status": {"allocatable": {"cpu": cpu, "memory": memory, "pods": str(pods)},
                       "capacity": {"cpu": cpu, "memory": memory, "pods": str(pods)}},
        })

    def add_pod_doc(self, name: str, namespace: str = "default",
                    app_id: str = "app-1", cpu: str = "500m",
                    memory: str = "128Mi") -> dict:
        return self.add("pods", {
            "metadata": {"name": name, "namespace": namespace,
                         "labels": {"applicationId": app_id},
                         "creationTimestamp": "2026-01-01T00:00:00Z"},
            "spec": {"schedulerName": "yunikorn",
                     "containers": [{"name": "sleep",
                                     "resources": {"requests": {"cpu": cpu,
                                                                "memory": memory}}}]},
            "status": {"phase": "Pending"},
        })
