"""Triggered flight recorder (round 20): bundle atomicity, the capped
ring, per-trigger debounce, staged pre-trigger evidence, per-source error
capture, reentrancy, and the metrics family."""
import json
import os
import threading

from yunikorn_tpu.obs.flightrec import (TRIGGERS, FlightRecorder,
                                        FlightRecorderOptions)
from yunikorn_tpu.obs.metrics import MetricsRegistry


def _rec(tmp_path, **kw):
    opts = FlightRecorderOptions(dir=str(tmp_path), **kw)
    return FlightRecorder(opts)


def test_disabled_recorder_never_touches_disk(tmp_path):
    fr = FlightRecorder(FlightRecorderOptions(dir=""))
    fr.add_source("x", lambda: {"a": 1})
    assert fr.record("manual", force=True) is None
    assert fr.list_recordings() == []
    assert fr.stats()["enabled"] is False


def test_bundle_contents_and_manifest(tmp_path):
    fr = _rec(tmp_path)
    fr.add_source("metrics", lambda: {"pods": 3})
    fr.stage("dead_shard_trace", {"traceEvents": []})
    path = fr.record("quarantine", reason="shard 1: wedged")
    assert path is not None and os.path.basename(path).endswith("quarantine")
    with open(os.path.join(path, "manifest.json")) as f:
        m = json.load(f)
    assert m["trigger"] == "quarantine"
    assert m["reason"] == "shard 1: wedged"
    assert sorted(m["files"]) == ["dead_shard_trace.json", "metrics.json"]
    assert m["source_errors"] == {}
    with open(os.path.join(path, "dead_shard_trace.json")) as f:
        assert json.load(f) == {"traceEvents": []}
    # staged evidence is consumed: the next bundle must not re-carry it
    p2 = fr.record("manual", force=True)
    with open(os.path.join(p2, "manifest.json")) as f:
        assert "dead_shard_trace.json" not in json.load(f)["files"]


def test_debounce_one_bundle_per_window_and_force(tmp_path):
    fr = _rec(tmp_path, debounce_s=3600.0)
    assert fr.record("slo_violation") is not None
    # a violation storm within the window yields ONE bundle
    assert fr.record("slo_violation") is None
    assert fr.stats()["debounced"] == 1
    # independent triggers debounce independently
    assert fr.record("quarantine") is not None
    # manual/REST dumps bypass the debounce
    assert fr.record("manual", force=True) is not None
    assert fr.record("manual", force=True) is not None
    assert fr.stats()["by_trigger"] == {"slo_violation": 1, "quarantine": 1,
                                        "manual": 2}


def test_ring_prunes_oldest_past_cap(tmp_path):
    fr = _rec(tmp_path, max_recordings=2)
    for _ in range(4):
        assert fr.record("manual", force=True) is not None
    recs = sorted(d for d in os.listdir(tmp_path) if d.startswith("rec-"))
    assert recs == ["rec-0003-manual", "rec-0004-manual"]  # newest two
    assert all(not d.startswith(".tmp") for d in os.listdir(tmp_path))


def test_failing_source_recorded_not_fatal(tmp_path):
    fr = _rec(tmp_path)
    fr.add_source("good", lambda: {"ok": True})
    fr.add_source("bad", lambda: 1 / 0)
    path = fr.record("breaker_exhausted", reason="path device")
    assert path is not None
    with open(os.path.join(path, "manifest.json")) as f:
        m = json.load(f)
    assert m["files"] == ["good.json"]
    assert "ZeroDivisionError" in m["source_errors"]["bad"]


def test_reentrant_trigger_from_source_noops(tmp_path):
    """A bundle source that re-enters record() (metrics snapshot -> SLO
    tick -> fresh violation edge) must no-op, not deadlock or recurse."""
    fr = _rec(tmp_path)
    inner = []

    def source():
        inner.append(fr.record("slo_violation", force=True))
        return {"ticked": True}

    fr.add_source("metrics", source)
    done = []
    t = threading.Thread(
        target=lambda: done.append(fr.record("manual", force=True)))
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "record() deadlocked on reentrancy"
    assert done and done[0] is not None
    assert inner == [None]  # the reentrant call dropped out
    assert fr.stats()["recordings"] == 1


def test_write_failure_returns_none_and_cleans_tmp(tmp_path):
    missing = os.path.join(str(tmp_path), "gone")
    fr = FlightRecorder(FlightRecorderOptions(dir=missing))
    os.makedirs(missing)
    os.rmdir(missing)  # dir vanishes before the dump (disk contract)
    # os.makedirs(tmp) recreates it, so break it harder: a FILE in the way
    with open(missing, "w") as f:
        f.write("not a dir")
    assert fr.record("manual", force=True) is None
    assert fr.stats()["recordings"] == 0


def test_metrics_family_by_trigger(tmp_path):
    m = MetricsRegistry()
    fr = FlightRecorder(FlightRecorderOptions(dir=str(tmp_path)), registry=m)
    c = m.get("flight_recordings_total")
    # stable zero series for every trigger (dashboards rate() them)
    assert all(c.value(trigger=t) == 0 for t in TRIGGERS)
    fr.record("watchdog_abandoned", reason="path device tier host")
    assert c.value(trigger="watchdog_abandoned") == 1
    assert c.value(trigger="slo_violation") == 0


def test_list_recordings_skips_partial_bundles(tmp_path):
    fr = _rec(tmp_path)
    fr.record("manual", force=True)
    # a concurrent writer's tmp dir must stay invisible to readers
    os.makedirs(os.path.join(str(tmp_path), ".tmp-0099"))
    recs = fr.list_recordings()
    assert len(recs) == 1 and recs[0]["trigger"] == "manual"
