"""Volume path e2e + binder semantics: static PV binding (PV node affinity
steering the batched solve), PV reservation exclusivity, WaitForFirstConsumer
with an external provisioner, CSINode attach limits, and the real-adapter PVC
flow over the fake API server.

Reference counterparts: volumebinding.NewVolumeBinder construction
(pkg/client/apifactory.go:92-165, 10-minute bind timeout), the volume-binding
assume/bind seams (pkg/cache/context.go:747-899), and the persistent_volume
E2E suite (test/e2e).
"""
import threading
import time

import pytest

from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import (CSINodeInfo, ObjectMeta,
                                         PersistentVolume,
                                         PersistentVolumeClaim, StorageClass,
                                         Volume, make_node, make_pod)
from yunikorn_tpu.shim.mock_scheduler import MockScheduler


@pytest.fixture
def sched():
    ms = MockScheduler()
    ms.init()
    ms.start()
    yield ms
    ms.stop()


def vol_pod(name, claim, app_id="vol-app", cpu=300):
    p = make_pod(
        name, cpu_milli=cpu, memory=2**27,
        labels={constants.LABEL_APPLICATION_ID: app_id},
        scheduler_name=constants.SCHEDULER_NAME)
    p.spec.volumes = [Volume(name="data", pvc_claim_name=claim)]
    return p


def test_static_pv_node_affinity_steers_placement(sched):
    """A zonal PV restricts its claim's pod to the PV's zone — through the
    batched solve (volume host-mask channel), not assume-failure retries."""
    for i in range(4):
        n = make_node(f"n{i}", cpu_milli=8000,
                      labels={"zone": "z-east" if i == 3 else "z-west"})
        sched.add_node(n)
    sched.cluster.add_storage_class(StorageClass(
        metadata=ObjectMeta(name="local"), provisioner=""))  # static-only
    sched.cluster.add_pv(PersistentVolume(
        metadata=ObjectMeta(name="pv-east"), capacity=2**31,
        storage_class="local", node_affinity={"zone": "z-east"}))
    sched.cluster.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name="claim-east", namespace="default"),
        storage_class="local", requested_storage=2**30))
    pod = sched.add_pod(vol_pod("east-pod", "claim-east"))
    sched.wait_for_task_state("vol-app", pod.uid, task_mod.BOUND)
    assert sched.get_pod_assignment(pod) == "n3"      # the only z-east node
    pvc = sched.cluster.get_pvc("default", "claim-east")
    assert pvc.bound and pvc.volume_name == "pv-east"
    assert sched.cluster.get_pv("pv-east").claim_ref == "default/claim-east"


def test_static_pv_exclusivity_second_claim_waits(sched):
    """One Available PV cannot satisfy two claims: the second pod stays
    pending until a second PV appears."""
    sched.add_node(make_node("n0", cpu_milli=8000))
    sched.cluster.add_storage_class(StorageClass(
        metadata=ObjectMeta(name="local"), provisioner=""))
    sched.cluster.add_pv(PersistentVolume(
        metadata=ObjectMeta(name="pv-a"), capacity=2**31, storage_class="local"))
    for c in ("c-a", "c-b"):
        sched.cluster.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name=c, namespace="default"),
            storage_class="local", requested_storage=2**30))
    p1 = sched.add_pod(vol_pod("vp-1", "c-a"))
    sched.wait_for_task_state("vol-app", p1.uid, task_mod.BOUND)
    p2 = sched.add_pod(vol_pod("vp-2", "c-b"))
    time.sleep(1.0)
    assert sched.get_pod_assignment(p2) == ""          # no PV left: pending
    sched.cluster.add_pv(PersistentVolume(
        metadata=ObjectMeta(name="pv-b"), capacity=2**31, storage_class="local"))
    sched.wait_for_task_state("vol-app", p2.uid, task_mod.BOUND)
    assert sched.cluster.get_pvc("default", "c-b").volume_name == "pv-b"


def test_wait_for_first_consumer_external_provisioner(sched):
    """WFFC: the binder writes the selected-node annotation and waits; an
    external provisioner (test thread) binds the claim; the pod then binds."""
    sched.cluster.auto_provision = False
    sched.add_node(make_node("n0", cpu_milli=8000))
    sched.cluster.add_storage_class(StorageClass(
        metadata=ObjectMeta(name="wffc"), provisioner="csi.example.com",
        volume_binding_mode="WaitForFirstConsumer"))
    sched.cluster.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name="wffc-claim", namespace="default"),
        storage_class="wffc", requested_storage=2**30))

    seen_node = []

    def provisioner():
        deadline = time.time() + 20
        while time.time() < deadline:
            pvc = sched.cluster.get_pvc("default", "wffc-claim")
            node = pvc.selected_node if pvc is not None else ""
            if node:
                seen_node.append(node)
                pvc.bound = True
                pvc.volume_name = "pv-provisioned"
                sched.cluster.update_pvc(pvc)
                return
            time.sleep(0.05)

    t = threading.Thread(target=provisioner, daemon=True)
    t.start()
    pod = sched.add_pod(vol_pod("wffc-pod", "wffc-claim"))
    sched.wait_for_task_state("vol-app", pod.uid, task_mod.BOUND)
    t.join(timeout=5)
    assert seen_node == ["n0"]                 # scheduler's decision handed over
    assert sched.cluster.get_pvc("default", "wffc-claim").volume_name == "pv-provisioned"


def test_slow_provisioner_does_not_block_other_binds(sched):
    """A claim stuck waiting on its provisioner must not stall unrelated
    pods (the volume wait runs on the bind pool, not the task thread)."""
    sched.cluster.auto_provision = False
    sched.add_node(make_node("n0", cpu_milli=8000))
    sched.cluster.add_storage_class(StorageClass(
        metadata=ObjectMeta(name="slow"), provisioner="csi.example.com",
        volume_binding_mode="WaitForFirstConsumer"))
    sched.cluster.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name="slow-claim", namespace="default"),
        storage_class="slow"))
    stuck = sched.add_pod(vol_pod("stuck-pod", "slow-claim"))
    plain = [sched.add_pod(make_pod(
        f"plain-{i}", cpu_milli=200, memory=2**26,
        labels={constants.LABEL_APPLICATION_ID: "vol-app"},
        scheduler_name=constants.SCHEDULER_NAME)) for i in range(4)]
    for p in plain:
        sched.wait_for_task_state("vol-app", p.uid, task_mod.BOUND)
    assert sched.get_pod_assignment(stuck) == ""       # still waiting
    # provisioner finally acts; the stuck pod completes
    pvc = sched.cluster.get_pvc("default", "slow-claim")
    pvc.bound = True
    pvc.volume_name = "pv-late"
    sched.cluster.update_pvc(pvc)
    sched.wait_for_task_state("vol-app", stuck.uid, task_mod.BOUND)


def test_known_class_without_provisioner_and_no_pv_pends(sched):
    """A claim whose StorageClass exists but cannot provision, with no
    matching PV, is unschedulable — the pod pends rather than binding."""
    sched.add_node(make_node("n0", cpu_milli=8000))
    sched.cluster.add_storage_class(StorageClass(
        metadata=ObjectMeta(name="static-only"), provisioner=""))
    sched.cluster.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name="orphan-claim", namespace="default"),
        storage_class="static-only"))
    pod = sched.add_pod(vol_pod("orphan-pod", "orphan-claim"))
    time.sleep(1.2)
    assert sched.get_pod_assignment(pod) == ""


def test_csinode_limits_node_attach_capacity(sched):
    """CSINode informer drives the node's attachable-volumes capacity
    (reference: the NodeVolumeLimits plugin reads CSINode)."""
    sched.add_node(make_node("n0", cpu_milli=16000))
    sched.cluster.add_csinode(CSINodeInfo(
        metadata=ObjectMeta(name="n0"),
        driver_limits={"csi.example.com": 2}))
    for i in range(3):
        sched.cluster.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name=f"lc{i}", namespace="default"),
            storage_class="anything"))
    pods = [sched.add_pod(vol_pod(f"lp-{i}", f"lc{i}", cpu=100))
            for i in range(3)]
    sched.wait_for_bound_count(2)
    time.sleep(0.5)
    bound = [p for p in pods if sched.get_pod_assignment(p)]
    assert len(bound) == 2                     # CSINode limit 2 caps the third


def test_real_adapter_pvc_flow_over_fake_apiserver():
    """PVC-bearing pod through the REAL adapter: PV/PVC/StorageClass served
    over HTTP, binder PUTs the claim/volume updates, pod binds (VERDICT r2
    missing #1: volume handling on the real-cluster path)."""
    import ssl

    from tests.fake_apiserver import FakeAPIServer
    from yunikorn_tpu.cache.context import Context
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.client.kube import KubeConfig, RealAPIProvider
    from yunikorn_tpu.conf.schedulerconf import get_holder, reset_for_tests
    from yunikorn_tpu.core.scheduler import CoreScheduler
    from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
    from yunikorn_tpu.shim.scheduler import KubernetesShim

    server = FakeAPIServer()
    port = server.start()
    cfg = KubeConfig(f"http://127.0.0.1:{port}", ssl.create_default_context())
    try:
        server.add_node_doc("vn0")
        server.add("storageclasses", {
            "metadata": {"name": "local"}, "provisioner": ""})
        server.add("persistentvolumes", {
            "metadata": {"name": "pv-0"},
            "spec": {"capacity": {"storage": "10Gi"},
                     "accessModes": ["ReadWriteOnce"],
                     "storageClassName": "local"},
            "status": {"phase": "Available"}})
        server.add("persistentvolumeclaims", {
            "metadata": {"name": "data-0", "namespace": "default"},
            "spec": {"accessModes": ["ReadWriteOnce"],
                     "storageClassName": "local",
                     "resources": {"requests": {"storage": "1Gi"}}}})
        server.add_pod_doc("stateful-0", app_id="vol-real-app",
                           volumes=[{"name": "data",
                                     "persistentVolumeClaim": {"claimName": "data-0"}}])

        reset_for_tests()
        get_holder().update_config_maps(
            [{"service.schedulingInterval": "0.05"}], initial=True)
        dispatch_mod.reset_dispatcher()
        provider = RealAPIProvider(cfg)
        cache = SchedulerCache()
        core = CoreScheduler(cache, interval=0.02)
        ctx = Context(provider, core, cache=cache)
        shim = KubernetesShim(provider, core, context=ctx)
        core.start()
        shim.run()
        try:
            deadline = time.time() + 25
            while time.time() < deadline and len(server.bindings) < 1:
                time.sleep(0.1)
            assert server.bindings == [("stateful-0", "vn0")]
            # the claim was bound through the HTTP write path
            pvc_doc = server.store["persistentvolumeclaims"]["default/data-0"]
            assert pvc_doc["spec"].get("volumeName") == "pv-0"
            pv_doc = server.store["persistentvolumes"]["pv-0"]
            assert pv_doc["spec"]["claimRef"]["name"] == "data-0"
            puts = [p for m, p in server.requests
                    if m == "PUT" and "persistentvolume" in p]
            assert puts                         # binder wrote over HTTP
        finally:
            core.stop()
            shim.stop()
            provider.stop()
    finally:
        server.stop()


def test_csinode_limit_survives_routine_node_update(sched):
    """A kubelet heartbeat (Node UPDATE with no attach info) must not revert
    the CSINode-driven attach cap to the default."""
    n0 = make_node("n0", cpu_milli=16000)
    sched.add_node(n0)
    sched.cluster.add_csinode(CSINodeInfo(
        metadata=ObjectMeta(name="n0"),
        driver_limits={"csi.example.com": 2}))
    for i in range(3):
        sched.cluster.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name=f"uc{i}", namespace="default"),
            storage_class="anything"))
    # routine status update: a fresh Node object with no VOLUME_ATTACH key
    sched.cluster.update_node(make_node("n0", cpu_milli=16000))
    pods = [sched.add_pod(vol_pod(f"up-{i}", f"uc{i}", cpu=100))
            for i in range(3)]
    sched.wait_for_bound_count(2)
    time.sleep(0.5)
    bound = [p for p in pods if sched.get_pod_assignment(p)]
    assert len(bound) == 2                     # limit still 2, not default


def test_codec_roundtrip_preserves_unmodeled_fields():
    """encode_pv/encode_pvc must merge binder mutations into the ORIGINAL
    API document: a PV without its volume source (csi/nfs/...) or a PVC
    stripped of volumeMode/resourceVersion is rejected by a real API server."""
    import dataclasses as _dc

    from yunikorn_tpu.client.k8s_codec import (decode_pv, decode_pvc,
                                               encode_pv, encode_pvc)

    pv_doc = {
        "apiVersion": "v1", "kind": "PersistentVolume",
        "metadata": {"name": "pv-x", "resourceVersion": "42"},
        "spec": {"capacity": {"storage": "10Gi"},
                 "accessModes": ["ReadWriteOnce"],
                 "storageClassName": "local",
                 "csi": {"driver": "csi.example.com", "volumeHandle": "h-1"},
                 "volumeMode": "Filesystem"},
        "status": {"phase": "Available"},
    }
    pv = decode_pv(pv_doc)
    bound = _dc.replace(pv, claim_ref="default/data-0", phase="Bound")
    out = encode_pv(bound)
    assert out["spec"]["csi"] == pv_doc["spec"]["csi"]       # source kept
    assert out["metadata"]["resourceVersion"] == "42"
    assert out["spec"]["claimRef"]["name"] == "data-0"
    assert out["status"]["phase"] == "Bound"

    pvc_doc = {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "data-0", "namespace": "default",
                     "resourceVersion": "7"},
        "spec": {"accessModes": ["ReadWriteOnce"],
                 "storageClassName": "local",
                 "volumeMode": "Block",
                 "selector": {"matchLabels": {"tier": "db"}},
                 "resources": {"requests": {"storage": "1Gi"}}},
    }
    pvc = decode_pvc(pvc_doc)
    bound_pvc = _dc.replace(pvc, volume_name="pv-x", bound=True)
    out = encode_pvc(bound_pvc)
    assert out["spec"]["volumeMode"] == "Block"              # immutable kept
    assert out["spec"]["selector"] == pvc_doc["spec"]["selector"]
    assert out["metadata"]["resourceVersion"] == "7"
    assert out["spec"]["volumeName"] == "pv-x"
    # encoding must not mutate the original raw document
    assert "volumeName" not in pvc_doc["spec"]


def test_csi_storage_capacity_gates_provisioning(sched):
    """A driver with storageCapacity=true: dynamic provisioning only counts
    as feasible on nodes covered by a CSIStorageCapacity segment that fits
    the claim (reference: volumebinding's CSIStorageCapacity checks)."""
    from yunikorn_tpu.common.objects import (CSIDriverInfo,
                                             CSIStorageCapacityInfo)

    for i in range(3):
        sched.add_node(make_node(f"cap-n{i}", cpu_milli=8000,
                                 labels={"topology.kubernetes.io/zone":
                                         f"z{i}"}))
    sched.cluster.add_storage_class(StorageClass(
        metadata=ObjectMeta(name="tracked"), provisioner="csi.tracked.io",
        volume_binding_mode="WaitForFirstConsumer"))
    sched.cluster.add_csi_driver(CSIDriverInfo(
        metadata=ObjectMeta(name="csi.tracked.io"), storage_capacity=True))
    # only zone z1 has provisionable capacity for 1Gi
    sched.cluster.add_csi_capacity(CSIStorageCapacityInfo(
        metadata=ObjectMeta(name="seg-z1", namespace="default"),
        storage_class="tracked",
        node_topology={"topology.kubernetes.io/zone": "z1"},
        capacity=2**31))
    sched.cluster.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name="cap-claim", namespace="default"),
        storage_class="tracked", requested_storage=2**30))
    pod = sched.add_pod(vol_pod("cap-pod", "cap-claim"))
    sched.wait_for_task_state("vol-app", pod.uid, task_mod.BOUND)
    assert sched.get_pod_assignment(pod) == "cap-n1"     # the only covered node

    # a claim bigger than every segment stays pending
    sched.cluster.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name="huge-claim", namespace="default"),
        storage_class="tracked", requested_storage=2**33))
    big = sched.add_pod(vol_pod("huge-pod", "huge-claim"))
    time.sleep(1.2)
    assert sched.get_pod_assignment(big) == ""


def test_volume_attachment_counts_against_attach_limit(sched):
    """VolumeAttachments from outside the scheduler occupy attach slots:
    with limit 2 and one foreign attachment, only one PVC pod fits."""
    from yunikorn_tpu.common.objects import VolumeAttachmentInfo

    sched.add_node(make_node("va-n0", cpu_milli=16000))
    sched.cluster.add_csinode(CSINodeInfo(
        metadata=ObjectMeta(name="va-n0"),
        driver_limits={"csi.example.com": 2}))
    sched.cluster.add_volume_attachment(VolumeAttachmentInfo(
        metadata=ObjectMeta(name="foreign-va"), attacher="csi.example.com",
        node_name="va-n0", pv_name="someone-elses-pv", attached=True))
    for i in range(2):
        sched.cluster.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name=f"va-c{i}", namespace="default"),
            storage_class="any"))
    pods = [sched.add_pod(vol_pod(f"va-p{i}", f"va-c{i}", cpu=100))
            for i in range(2)]
    sched.wait_for_bound_count(1)
    time.sleep(0.8)
    bound = [p for p in pods if sched.get_pod_assignment(p)]
    assert len(bound) == 1        # 2-slot limit minus 1 foreign attachment
    # the attachment is released -> the second pod fits
    sched.cluster.delete_volume_attachment("foreign-va")
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(sched.get_pod_assignment(p) for p in pods):
            break
        time.sleep(0.1)
    assert all(sched.get_pod_assignment(p) for p in pods)


def test_static_pv_satisfies_tracked_class_without_segments(sched):
    """A pre-provisioned static PV serves a claim of a capacity-tracked
    class even when NO CSIStorageCapacity segment exists (binder order:
    static match first; encoder mask must agree)."""
    from yunikorn_tpu.common.objects import CSIDriverInfo

    sched.add_node(make_node("st-n0", cpu_milli=8000))
    sched.cluster.add_storage_class(StorageClass(
        metadata=ObjectMeta(name="tracked2"), provisioner="csi.t2.io"))
    sched.cluster.add_csi_driver(CSIDriverInfo(
        metadata=ObjectMeta(name="tracked2-drv"), storage_capacity=True))
    sched.cluster.add_csi_driver(CSIDriverInfo(
        metadata=ObjectMeta(name="csi.t2.io"), storage_capacity=True))
    sched.cluster.add_pv(PersistentVolume(
        metadata=ObjectMeta(name="restored-pv"), capacity=2**31,
        storage_class="tracked2"))
    sched.cluster.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name="restored-claim", namespace="default"),
        storage_class="tracked2", requested_storage=2**30))
    pod = sched.add_pod(vol_pod("restore-pod", "restored-claim"))
    sched.wait_for_task_state("vol-app", pod.uid, task_mod.BOUND)
    pvc = sched.cluster.get_pvc("default", "restored-claim")
    assert pvc.bound and pvc.volume_name == "restored-pv"


def test_unsupported_capacity_topology_fails_closed():
    """A segment whose nodeTopology uses expressions the model can't
    represent must NOT widen to all nodes."""
    from yunikorn_tpu.client.k8s_codec import decode_csistoragecapacity
    from yunikorn_tpu.common.objects import make_node as mk

    cap = decode_csistoragecapacity({
        "metadata": {"name": "multi", "namespace": "default"},
        "storageClassName": "fast",
        "nodeTopology": {"matchExpressions": [
            {"key": "zone", "operator": "In", "values": ["a", "b"]}]},
        "capacity": "10Gi"})
    assert cap.topology_unsupported
    assert not cap.covers_node(mk("anynode", labels={"zone": "c"}))
    assert not cap.covers_node(mk("anode", labels={"zone": "a"}))


def test_nil_topology_segment_matches_no_nodes():
    """Upstream semantics: a CSIStorageCapacity with NO nodeTopology matches
    no node (nil selector = labels.Nothing), unlike an empty selector."""
    from yunikorn_tpu.client.k8s_codec import decode_csistoragecapacity
    from yunikorn_tpu.common.objects import make_node as mk

    nil = decode_csistoragecapacity({
        "metadata": {"name": "nil", "namespace": "default"},
        "storageClassName": "fast", "capacity": "10Gi"})
    assert not nil.covers_node(mk("n", labels={"zone": "a"}))
    empty = decode_csistoragecapacity({
        "metadata": {"name": "empty", "namespace": "default"},
        "storageClassName": "fast", "nodeTopology": {}, "capacity": "10Gi"})
    assert empty.covers_node(mk("n", labels={"zone": "a"}))
