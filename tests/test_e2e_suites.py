"""End-to-end suite breadth: in-process equivalents of reference ginkgo
suites not yet covered by the other scenario files — user/group limits
(reference test/e2e/user_group_limit) and concurrent Spark-style jobs over a
hierarchical queue tree (reference test/e2e/spark_jobs_scheduling). Full
scheduler (real core + real shim + FakeCluster), behavior + no-drift
invariants.
"""
import json
import time

import pytest

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.shim.mock_scheduler import MockScheduler

from tests.test_context_storm import assert_no_drift, wait_bound


LIMITS_CONF = """
partitions:
  - name: default
    queues:
      - name: root
        submitacl: "*"
        queues:
          - name: limited
            limits:
              - users: [alice]
                maxresources: {vcore: 1}
          - name: open
"""

SPARK_CONF = """
partitions:
  - name: default
    queues:
      - name: root
        submitacl: "*"
        queues:
          - name: spark
            queues:
              - name: team-a
                resources:
                  guaranteed: {vcore: 4}
              - name: team-b
                resources:
                  guaranteed: {vcore: 4}
"""


def user_pod(name, app, queue, user, cpu=400):
    p = make_pod(name, cpu_milli=cpu, memory=2**26,
                 labels={"applicationId": app, "queue": queue},
                 scheduler_name=constants.SCHEDULER_NAME)
    p.metadata.annotations[constants.ANNOTATION_USER_INFO] = json.dumps(
        {"user": user, "groups": [f"{user}-group"]})
    return p


def test_user_group_limit_e2e():
    """A per-user maxresources limit on a queue caps ONE user's footprint
    while other users keep scheduling (reference user_group_limit suite)."""
    ms = MockScheduler()
    ms.init(LIMITS_CONF)
    try:
        ms.add_node(make_node("ul-n0", cpu_milli=16000, memory=16 * 2**30))
        ms.start()
        # alice may hold at most 1 vcore (1000m) in root.limited → 2 of her
        # 400m pods fit, the 3rd must stay pending
        alice = [user_pod(f"al{i}", "alice-app", "root.limited", "alice")
                 for i in range(3)]
        ms.add_pods(alice)
        assert wait_bound(ms, alice, timeout=20, expect=2) == 2
        time.sleep(1.0)
        bound_alice = [p for p in alice if ms.get_pod_assignment(p)]
        assert len(bound_alice) == 2, "alice exceeded her user limit"
        # bob is not limited: all his pods flow through the same queue
        bob = [user_pod(f"bo{i}", "bob-app", "root.limited", "bob")
               for i in range(4)]
        ms.add_pods(bob)
        assert wait_bound(ms, bob, timeout=20) == 4
        # alice's third pod schedules once one of hers finishes (snapshot the
        # pending set BEFORE freeing quota — the scheduler races the release)
        pending_alice = [p for p in alice if not ms.get_pod_assignment(p)]
        ms.succeed_pod(bound_alice[0])
        assert wait_bound(ms, pending_alice, timeout=20) == 1
        assert_no_drift(ms)
    finally:
        ms.stop()


def spark_job(app_id, queue, n_executors):
    driver = make_pod(f"{app_id}-driver", cpu_milli=500, memory=2**27,
                      labels={"applicationId": app_id, "queue": queue,
                              "spark-role": "driver"},
                      scheduler_name=constants.SCHEDULER_NAME)
    executors = [
        make_pod(f"{app_id}-exec-{i}", cpu_milli=250, memory=2**26,
                 labels={"applicationId": app_id, "queue": queue,
                         "spark-role": "executor"},
                 scheduler_name=constants.SCHEDULER_NAME)
        for i in range(n_executors)
    ]
    return driver, executors


def test_spark_jobs_scheduling_e2e():
    """Several concurrent Spark-style jobs (driver + executors per app) over
    a hierarchical queue tree: every pod of every job binds, drivers are the
    app originators, and queue accounting survives job completion
    (reference spark_jobs_scheduling suite)."""
    ms = MockScheduler()
    ms.init(SPARK_CONF)
    try:
        ms.add_nodes([make_node(f"sp-n{i}", cpu_milli=8000, memory=16 * 2**30)
                      for i in range(4)])
        ms.start()
        jobs = []
        for j in range(4):
            queue = "root.spark.team-a" if j % 2 == 0 else "root.spark.team-b"
            driver, executors = spark_job(f"spark-{j}", queue, 6)
            # driver submits first (the Spark operator's order), executors
            # follow while other jobs' pods interleave
            ms.add_pod(driver)
            jobs.append((driver, executors))
        for _, executors in jobs:
            ms.add_pods(executors)
        everything = [p for d, ex in jobs for p in [d] + ex]
        assert wait_bound(ms, everything, timeout=60) == len(everything)
        # drivers are the originators of their apps
        for driver, _ in jobs:
            app = ms.context.get_application(
                driver.metadata.labels["applicationId"])
            task = app.get_task(driver.uid)
            assert task is not None and task.originator
        # a finished job releases its queue usage
        d0, ex0 = jobs[0]
        for p in [d0] + ex0:
            ms.succeed_pod(p)
        deadline = time.time() + 15
        while time.time() < deadline:
            core_app = ms.core.partition.applications.get("spark-0")
            if core_app is not None and not core_app.allocations:
                break
            time.sleep(0.1)
        core_app = ms.core.partition.applications.get("spark-0")
        assert core_app is not None and not core_app.allocations
        assert_no_drift(ms)
    finally:
        ms.stop()
