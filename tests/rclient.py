"""RClient: REST test helper with wait-for-state combinators.

Reference analog: test/e2e/framework/helpers/yunikorn/rest_api_utils.go —
the ginkgo suites drive the scheduler's /ws/v1 surface through a typed client
with retrying wait helpers. Tests (and operators) use this against a live
RestServer, exactly as the reference e2e drives a deployed scheduler.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Callable, Optional


class RClient:
    def __init__(self, port: int, host: str = "127.0.0.1"):
        self.base = f"http://{host}:{port}"

    # ------------------------------------------------------------- raw verbs
    def get(self, path: str, timeout: float = 5.0):
        with urllib.request.urlopen(self.base + path, timeout=timeout) as r:
            return json.loads(r.read())

    def post(self, path: str, body: Optional[str] = None):
        req = urllib.request.Request(
            self.base + path,
            data=body.encode() if body is not None else None,
            method="POST")
        with urllib.request.urlopen(req, timeout=5.0) as r:
            return json.loads(r.read())

    # ------------------------------------------------------------ typed gets
    def health(self) -> bool:
        try:
            return bool(self.get("/ws/v1/health").get("Healthy"))
        except (urllib.error.URLError, ConnectionError):
            return False

    def queues(self, partition: str = "default"):
        return self.get(f"/ws/v1/partition/{partition}/queues")

    def apps(self, partition: str = "default"):
        return self.get(f"/ws/v1/partition/{partition}/applications")

    def app(self, app_id: str, partition: str = "default"):
        return self.apps(partition).get(app_id)

    def nodes(self, partition: str = "default"):
        return self.get(f"/ws/v1/partition/{partition}/nodes")

    def metrics(self):
        return self.get("/ws/v1/metrics")

    def user_usage(self, partition: str = "default"):
        return self.get(f"/ws/v1/partition/{partition}/usage/users")

    def events(self, count: int = 1000):
        return self.get(f"/ws/v1/events/batch?count={count}")["EventRecords"]

    def full_state_dump(self):
        return self.get("/ws/v1/fullstatedump")

    def validate_conf(self, queues_yaml: str):
        return self.post("/ws/v1/validate-conf", queues_yaml)

    # -------------------------------------------------- wait-for combinators
    def wait_for(self, predicate: Callable[[], bool], timeout: float = 10.0,
                 interval: float = 0.1, what: str = "condition") -> None:
        """Poll until predicate or timeout; on timeout, dump triage state
        (reference test/e2e/framework/helpers wrappers.go:36-135 dumps the
        cluster + scheduler state on every failure) before raising."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if predicate():
                    return
            except (urllib.error.URLError, ConnectionError, KeyError):
                pass
            time.sleep(interval)
        raise TimeoutError(
            f"timed out waiting for {what}; triage: {self.triage_dump()}")

    def triage_dump(self, max_len: int = 4000) -> str:
        """Best-effort state dump for failure triage: queues, apps, node
        count, last events — truncated so assertion output stays readable."""
        out = {}
        for name, fn in (("queues", self.queues), ("apps", self.apps),
                         ("nodes", lambda: len(self.nodes())),
                         ("events", lambda: self.events(50))):
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 — triage must never raise
                out[name] = f"<{type(e).__name__}: {e}>"
        s = json.dumps(out, default=str)
        return s[:max_len] + ("…" if len(s) > max_len else "")

    def wait_for_health(self, timeout: float = 10.0) -> None:
        self.wait_for(self.health, timeout, what="scheduler health")

    def wait_for_app_state(self, app_id: str, state: str,
                           partition: str = "default",
                           timeout: float = 10.0) -> None:
        self.wait_for(
            lambda: (self.app(app_id, partition) or {}).get("state") == state,
            timeout, what=f"app {app_id} state {state}")

    def wait_for_allocation_count(self, app_id: str, count: int,
                                  partition: str = "default",
                                  timeout: float = 10.0) -> None:
        self.wait_for(
            lambda: len((self.app(app_id, partition) or {}).get("allocations", [])) == count,
            timeout, what=f"app {app_id} to hold {count} allocations")

    def wait_for_node_count(self, count: int, partition: str = "default",
                            timeout: float = 10.0) -> None:
        self.wait_for(lambda: len(self.nodes(partition)) == count,
                      timeout, what=f"{count} nodes")
