"""The five BASELINE.md benchmark configurations as functional tests (scaled
down for CPU): the shapes the driver's kwok-perf-test analog measures.

1. 100 nodes / 1k sleep pods, default queue
2. flat queue, resource-fit only (scaled; the full 10k/50k runs in bench.py)
3. Spark-on-K8s: executors under hierarchical queues + DRF fair-share
4. gang: placement-group all-or-nothing (covered at full fidelity in
   test_gang_e2e.py; here the Ray-job shape)
5. multi-resource bin-pack: GPU+CPU+mem with node-affinity + taints
"""
import json

import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.client.synthetic import (
    make_kwok_nodes,
    make_mixed_binpack_pods,
    make_sleep_pods,
)
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import Taint, Toleration, make_node, make_pod
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import (
    AddApplicationRequest,
    AllocationAsk,
    AllocationRequest,
    ApplicationRequest,
    NodeAction,
    NodeInfo,
    NodeRequest,
    RegisterResourceManagerRequest,
    UserGroupInfo,
)
from yunikorn_tpu.core.scheduler import CoreScheduler

from test_core import RecordingCallback

SPARK_YAML = """
partitions:
  - name: default
    nodesortpolicy: {type: binpacking}
    queues:
      - name: root
        queues:
          - name: spark
            queues:
              - name: team-a
                resources:
                  guaranteed: {vcore: 8}
              - name: team-b
                resources:
                  guaranteed: {vcore: 8}
"""


def build_core(nodes, queues_yaml=""):
    cache = SchedulerCache()
    cb = RecordingCallback()
    core = CoreScheduler(cache)
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="t", policy_group="queues",
                                       config=queues_yaml), cb)
    infos = []
    for n in nodes:
        cache.update_node(n)
        infos.append(NodeInfo(node_id=n.name, action=NodeAction.CREATE))
    core.update_node(NodeRequest(nodes=infos))
    return cache, cb, core


def asks_for(core, pods, app_id):
    return [AllocationAsk(p.uid, app_id, get_pod_resource(p), pod=p,
                          priority=p.spec.priority or 0) for p in pods]


def test_config1_sleep_pods_default_queue():
    nodes = make_kwok_nodes(20)
    cache, cb, core = build_core(nodes)
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="sleep-app", queue_name="root.default",
        user=UserGroupInfo(user="perf"))]))
    pods = make_sleep_pods(200, "sleep-app")
    core.update_allocation(AllocationRequest(asks=asks_for(core, pods, "sleep-app")))
    assert core.schedule_once() == 200
    # all fit: 20 nodes × 110-pod cap ≥ 200 and cpu/memory ample
    assert len(cb.allocations) == 200


def test_config3_spark_executors_hierarchical_drf():
    """5k executors scaled to 200; two teams under root.spark share fairly."""
    nodes = make_kwok_nodes(10, cpu_milli=32000)
    cache, cb, core = build_core(nodes, SPARK_YAML)
    for team in ("team-a", "team-b"):
        core.update_application(ApplicationRequest(new=[AddApplicationRequest(
            application_id=f"spark-{team}", queue_name=f"root.spark.{team}",
            user=UserGroupInfo(user=team))]))
    # driver + executors per app (spark shape: 1 driver, N executors)
    all_asks = []
    for team in ("team-a", "team-b"):
        driver = make_pod(f"{team}-driver", cpu_milli=1000, memory=2**30)
        execs = [make_pod(f"{team}-exec-{i}", cpu_milli=1000, memory=2**30)
                 for i in range(100)]
        all_asks.extend(asks_for(core, [driver] + execs, f"spark-{team}"))
    core.update_allocation(AllocationRequest(asks=all_asks))
    total = 0
    for _ in range(6):
        total += core.schedule_once()
        if total >= 202:
            break
    assert total == 202
    # fair share: both teams fully placed, usage equal
    qa = core.queues.resolve("root.spark.team-a", create=False)
    qb = core.queues.resolve("root.spark.team-b", create=False)
    assert qa.allocated.get("cpu") == qb.allocated.get("cpu") == 101000


def test_config4_ray_gang_shape():
    """2k Ray jobs × 32 scaled to 8 jobs × 8: all-or-nothing via task groups.

    Full placeholder lifecycle is covered in test_gang_e2e; this validates the
    core-side placement-group shape at multiplicity.
    """
    nodes = make_kwok_nodes(16, cpu_milli=16000)
    cache, cb, core = build_core(nodes)
    for j in range(8):
        core.update_application(ApplicationRequest(new=[AddApplicationRequest(
            application_id=f"ray-{j}", queue_name="root.default",
            user=UserGroupInfo(user="ray"),
            gang_scheduling_style="Hard")]))
        ph_asks = [
            AllocationAsk(f"ray-{j}-ph-{i}", f"ray-{j}",
                          get_pod_resource(make_pod(f"ray-{j}-ph-{i}", cpu_milli=500,
                                                    memory=2**28)),
                          placeholder=True, task_group_name="workers",
                          pod=make_pod(f"rayp-{j}-{i}", cpu_milli=500, memory=2**28))
            for i in range(8)
        ]
        core.update_allocation(AllocationRequest(asks=ph_asks))
    n = core.schedule_once()
    assert n == 64  # every job's full gang reserved
    # real workers replace placeholders in place
    for j in range(8):
        real = [AllocationAsk(f"ray-{j}-w-{i}", f"ray-{j}",
                              get_pod_resource(make_pod(f"rayw-{j}-{i}", cpu_milli=500,
                                                        memory=2**28)),
                              task_group_name="workers",
                              pod=make_pod(f"rayw-{j}-{i}", cpu_milli=500, memory=2**28))
                for i in range(8)]
        core.update_allocation(AllocationRequest(asks=real))
    core.schedule_once()
    replaced = [r for r in cb.releases
                if r.termination_type.value == "PLACEHOLDER_REPLACED"]
    assert len(replaced) == 64


def test_config5_mixed_binpack_affinity_taints():
    """GPU+CPU+mem pods with node affinity + taints (20k nodes scaled to 64)."""
    gpu_taint = Taint(key="accelerator", value="gpu", effect="NoSchedule")
    nodes = []
    for i in range(32):
        nodes.append(make_node(f"cpu-{i}", cpu_milli=32000, memory=64 * 2**30, pods=110))
    for i in range(32):
        nodes.append(make_node(
            f"gpu-{i}", cpu_milli=32000, memory=64 * 2**30, pods=110,
            labels={"accelerator": "gpu"}, taints=[gpu_taint],
            extra_resources={"nvidia.com/gpu": 8}))
    cache, cb, core = build_core(nodes)
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="mix", queue_name="root.default",
        user=UserGroupInfo(user="ml"))]))
    pods = make_mixed_binpack_pods(300, "mix", seed=7)
    # GPU pods must target (and tolerate) the GPU pool
    for p in pods:
        if any("nvidia.com/gpu" in c.resources_requests for c in p.spec.containers):
            p.spec.node_selector = {"accelerator": "gpu"}
            p.spec.tolerations = [Toleration(key="accelerator", operator="Equal",
                                             value="gpu", effect="NoSchedule")]
    core.update_allocation(AllocationRequest(asks=asks_for(core, pods, "mix")))
    total = 0
    for _ in range(4):
        total += core.schedule_once()
    assert total == 300
    # every GPU pod landed on a GPU node; no CPU pod on a tainted node
    for alloc in cb.allocations:
        pod = next(p for p in pods if p.uid == alloc.allocation_key)
        is_gpu = any("nvidia.com/gpu" in c.resources_requests for c in pod.spec.containers)
        if is_gpu:
            assert alloc.node_id.startswith("gpu-")
        else:
            assert alloc.node_id.startswith("cpu-")
    # exact GPU accounting: no node exceeds 8 GPUs
    gpu_used = {}
    for alloc in cb.allocations:
        g = alloc.resource.get("nvidia.com/gpu")
        if g:
            gpu_used[alloc.node_id] = gpu_used.get(alloc.node_id, 0) + g
    assert all(v <= 8 for v in gpu_used.values())
