"""SLO engine + trace-replay proving-ground tests (round 14).

Covers the streaming quantile sketch (accuracy vs exact percentiles,
window expiry), multi-window burn-rate verdict transitions
(ok -> burning -> violated, edge-triggered violation counting), the
exposition contract of the slo_* families, the promtext bucket-interpolation
helper, the core's new taps (mis-eviction ledger, first-cycle gauge,
staleness probe), the health-readiness flip on a violated
availability-class objective, the Grafana round-14 row's exposition-prefix
rule, and the trace generator's seeded-determinism contract.
"""
import json
import math
import os
import sys
import time

import pytest

from yunikorn_tpu.obs.metrics import MetricsRegistry
from yunikorn_tpu.obs.promtext import (
    histogram_quantile,
    parse_exposition,
    quantile_from_buckets,
    validate_exposition,
)
from yunikorn_tpu.obs.slo import (
    OBJECTIVES,
    BurnWindow,
    QuantileSketch,
    SloEngine,
    SloOptions,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t0: float = 1_000_000.0):
        self.t = t0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# ---------------------------------------------------------------------------
# QuantileSketch
# ---------------------------------------------------------------------------
def test_sketch_quantiles_track_exact_percentiles():
    import random

    rng = random.Random(7)
    sk = QuantileSketch(window_s=60.0, sub_s=1.0)
    now = 1000.0
    values = [rng.lognormvariate(-2.0, 1.0) for _ in range(5000)]
    for v in values:
        sk.observe(v, now)
    values.sort()
    for q in (0.5, 0.9, 0.99):
        exact = values[int(q * (len(values) - 1))]
        est = sk.quantile(q, now)
        # log-bucket sketch: ~5% relative error per bucket, allow 2 buckets
        assert est is not None
        assert exact / 1.12 <= est <= exact * 1.12, (q, exact, est)
    assert sk.count(now) == 5000


def test_sketch_window_expiry_and_count_over():
    sk = QuantileSketch(window_s=30.0, sub_s=1.0)
    for i in range(10):
        sk.observe(0.1, 1000.0 + i)   # old fast observations
    for i in range(5):
        sk.observe(5.0, 1020.0 + i)   # newer slow ones
    # at t=1024 the 0.1s observations fell out of a 10s sub-window query
    total, bad = sk.count_over(1.0, now=1024.0, window_s=10.0)
    assert total == 5 and bad == 5
    # the full window still sees both generations
    total, bad = sk.count_over(1.0, now=1024.0, window_s=30.0)
    assert total == 15 and bad == 5
    assert sk.quantile(0.5, 1024.0, window_s=10.0) == pytest.approx(
        5.0, rel=0.1)
    # everything expires past the sketch's own window
    sk.observe(1.0, 1100.0)
    assert sk.count(1100.0) == 1


def test_sketch_memory_is_bounded():
    sk = QuantileSketch(window_s=10.0, sub_s=1.0)
    for i in range(10_000):
        sk.observe(1.0, 1000.0 + i * 0.5)
    assert len(sk._subs) <= sk.n_sub + 2


# ---------------------------------------------------------------------------
# BurnWindow
# ---------------------------------------------------------------------------
def test_burn_window_counts_and_expiry():
    w = BurnWindow(window_s=20.0, sub_s=1.0)
    for i in range(10):
        w.record(True, 1000.0 + i)
    w.record(False, 1009.0, n=5)
    good, bad = w.counts(1009.0)
    assert (good, bad) == (10, 5)
    assert w.bad_fraction(1009.0) == pytest.approx(5 / 15)
    # everything expires out of the window
    good, bad = w.counts(1100.0)
    assert (good, bad) == (0, 0)
    assert w.bad_fraction(1100.0) is None


# ---------------------------------------------------------------------------
# Engine verdicts + burn rates
# ---------------------------------------------------------------------------
def _engine(clock, **opt):
    opts = SloOptions(fast_window_s=30.0, slow_window_s=120.0,
                      pod_e2e_p99_s=1.0, cycle_staleness_s=5.0,
                      burn_fast_threshold=6.0, **opt)
    reg = MetricsRegistry()
    eng = SloEngine(opts, registry=reg, now_fn=clock)
    return eng, reg


def test_latency_objective_ok_burning_violated_and_edge_counting():
    clock = FakeClock()
    eng, reg = _engine(clock)
    # 1000 good observations: ok
    eng.observe_e2e([0.1] * 1000)
    ev = eng.tick()["pod_e2e_p99"]
    assert ev["verdict"] == "ok" and ev["burn_rate"]["fast"] == 0.0

    # age the good history out of the FAST window (still inside slow),
    # then a 40% bad burst in the fast window: fast burns >> threshold
    # while the slow window's burn stays diluted under 1.0 -> burning
    clock.advance(50.0)
    eng.observe_e2e([0.1] * 100 + [5.0] * 8)
    ev = eng.tick()["pod_e2e_p99"]
    assert ev["verdict"] == "burning", ev
    assert ev["burn_rate"]["fast"] == pytest.approx(8 / 108 / 0.01,
                                                    rel=1e-3)
    assert ev["burn_rate"]["slow"] == pytest.approx(8 / 1108 / 0.01,
                                                    rel=1e-3)
    assert ev["burn_rate"]["slow"] < 1.0

    # flood bad past the slow window's budget -> violated, counted ONCE
    eng.observe_e2e([5.0] * 2000)
    ev = eng.tick()["pod_e2e_p99"]
    assert ev["verdict"] == "violated"
    assert ev["value"] is not None and ev["value"] > 1.0  # sketch p99
    v = reg.get("slo_violations_total")
    assert v.value(objective="pod_e2e_p99") == 1
    eng.tick()
    assert v.value(objective="pod_e2e_p99") == 1  # edge-triggered

    # recovery: the bad run ages out of both windows -> ok again, and a NEW
    # violation episode counts a second time
    clock.advance(200.0)
    eng.observe_e2e([0.1] * 100)
    assert eng.tick()["pod_e2e_p99"]["verdict"] == "ok"
    eng.observe_e2e([5.0] * 100)
    assert eng.tick()["pod_e2e_p99"]["verdict"] == "violated"
    assert v.value(objective="pod_e2e_p99") == 2


def test_staleness_objective_follows_probe():
    clock = FakeClock()
    eng, _ = _engine(clock)
    ages = {"default": 0.5}
    eng._staleness_fn = lambda: ages
    assert eng.tick()["cycle_staleness"]["verdict"] == "ok"
    ages = {"default": 7.5}  # over the 5s target -> violated immediately
    ev = eng.tick()["cycle_staleness"]
    assert ev["verdict"] == "violated" and ev["value"] == 7.5
    assert ev["partitions"] == {"default": 7.5}
    # recovered loop: current age fine; recent bad samples keep the fast
    # window burning (budget was consumed) without re-violating
    ages = {"default": 0.2}
    for _ in range(3):
        clock.advance(1.0)
        eng.tick()
    ev = eng.tick()["cycle_staleness"]
    assert ev["verdict"] == "burning"
    # far enough out, the bad sample ages out of the fast window -> ok
    clock.advance(40.0)
    for _ in range(30):
        clock.advance(1.0)
        eng.tick()
    assert eng.verdict("cycle_staleness") == "ok"


def test_dwell_objective_budget_and_min_samples():
    clock = FakeClock()
    eng, _ = _engine(clock, degraded_dwell_budget=0.3)
    degraded = {}
    eng._degraded_fn = lambda: degraded
    # a couple of degraded ticks right after start must NOT violate (no
    # evidentiary weight yet) — at most burning
    degraded = {"assign": "cpu"}
    for _ in range(3):
        clock.advance(1.0)
        eng.tick()
    assert eng.verdict("degraded_dwell") in ("ok", "burning")
    # chronic dwell past MIN_RATIO_SAMPLES violates
    for _ in range(SloEngine.MIN_RATIO_SAMPLES + 5):
        clock.advance(1.0)
        eng.tick()
    assert eng.verdict("degraded_dwell") == "violated"
    # full recovery drains the windows
    degraded = {}
    for _ in range(130):
        clock.advance(1.0)
        eng.tick()
    assert eng.verdict("degraded_dwell") == "ok"


def test_misevict_objective_zero_tolerance_and_reset():
    clock = FakeClock()
    eng, reg = _engine(clock)
    counter = [0.0]
    eng._misevict_fn = lambda: counter[0]
    assert eng.tick()["mis_evictions"]["verdict"] == "ok"
    counter[0] = 3.0
    ev = eng.tick()["mis_evictions"]
    assert ev["verdict"] == "violated" and ev["value"] == 3
    assert reg.get("slo_violations_total").value(
        objective="mis_evictions") == 1
    # reset() re-bases the seen counter: no double count on the next tick
    eng.reset()
    assert eng.tick()["mis_evictions"]["verdict"] == "ok"
    assert eng.violations()["mis_evictions"] == 0


def test_coldstart_objective_budget():
    clock = FakeClock()
    eng, _ = _engine(clock, cold_start_budget_ms=100.0)
    val = [None]
    eng._coldstart_fn = lambda: val[0]
    assert eng.tick()["aot_cold_start"]["verdict"] == "ok"
    val[0] = 50.0
    ev = eng.tick()["aot_cold_start"]
    assert ev["verdict"] == "ok" and ev["burn_rate"]["fast"] == 0.5
    val[0] = 250.0
    assert eng.tick()["aot_cold_start"]["verdict"] == "violated"


def test_engine_exposition_contract():
    clock = FakeClock()
    eng, reg = _engine(clock)
    eng.observe_e2e([0.1, 0.2, 5.0])
    eng.tick()
    text = reg.expose()
    errs = validate_exposition(text, required=(
        "yunikorn_slo_burn_rate", "yunikorn_slo_violations_total",
        "yunikorn_slo_verdict", "yunikorn_slo_objective_value"))
    assert errs == [], errs
    fams = parse_exposition(text)
    assert fams["yunikorn_slo_burn_rate"].kind == "gauge"
    assert fams["yunikorn_slo_violations_total"].kind == "counter"
    burn = fams["yunikorn_slo_burn_rate"]
    assert {s.labels["window"] for s in burn.samples} == {"fast", "slow"}
    assert ({s.labels["objective"] for s in burn.samples}
            == set(OBJECTIVES))
    # violations expose a stable zero series per objective (rate()-able)
    viols = fams["yunikorn_slo_violations_total"]
    assert {s.labels["objective"] for s in viols.samples} == set(OBJECTIVES)


def test_engine_report_shape():
    clock = FakeClock()
    eng, _ = _engine(clock)
    rep = eng.report()
    assert set(rep["objectives"]) == set(OBJECTIVES)
    for name, obj in rep["objectives"].items():
        assert obj["verdict"] in ("ok", "burning", "violated")
        assert obj["availability"] == OBJECTIVES[name][0]
        assert "burn_rate" in obj and "violations" in obj
    assert rep["healthy"] is True and rep["violated"] == []


# ---------------------------------------------------------------------------
# promtext histogram_quantile (bucket interpolation)
# ---------------------------------------------------------------------------
def test_quantile_from_buckets_interpolation():
    buckets = [(0.1, 10.0), (0.5, 30.0), (1.0, 40.0), (math.inf, 40.0)]
    # p50: rank 20 -> inside (0.1, 0.5]: 0.1 + 0.4 * (20-10)/20 = 0.3
    assert quantile_from_buckets(0.5, buckets) == pytest.approx(0.3)
    # p90: rank 36 -> inside (0.5, 1.0]: 0.5 + 0.5 * (36-30)/10 = 0.8
    assert quantile_from_buckets(0.9, buckets) == pytest.approx(0.8)
    # rank in the first bucket interpolates from 0
    assert quantile_from_buckets(0.1, buckets) == pytest.approx(
        0.1 * (4.0 / 10.0))
    # +Inf bucket clamps to the highest finite edge
    buckets_tail = [(0.1, 10.0), (math.inf, 20.0)]
    assert quantile_from_buckets(0.99, buckets_tail) == pytest.approx(0.1)
    # degenerate / invalid inputs
    assert quantile_from_buckets(0.5, []) is None
    assert quantile_from_buckets(0.5, [(1.0, 5.0)]) is None  # no +Inf
    assert quantile_from_buckets(0.5, [(math.inf, 0.0)]) is None  # empty
    with pytest.raises(ValueError):
        quantile_from_buckets(1.5, buckets)


def test_histogram_quantile_over_parsed_exposition():
    reg = MetricsRegistry()
    h = reg.histogram("demo_latency_seconds", "d", labelnames=("stage",),
                      buckets=(0.1, 0.5, 1.0))
    h.observe_batch([0.05] * 10 + [0.3] * 20 + [0.7] * 10, stage="s")
    fams = parse_exposition(reg.expose())
    fam = fams["yunikorn_demo_latency_seconds"]
    q50 = histogram_quantile(0.5, fam, labels={"stage": "s"})
    assert 0.1 <= q50 <= 0.5
    assert histogram_quantile(0.5, fam, labels={"stage": "nope"}) is None
    reg.gauge("demo_gauge", "g").set(1.0)
    fams = parse_exposition(reg.expose())
    with pytest.raises(ValueError):
        histogram_quantile(0.5, fams["yunikorn_demo_gauge"])


# ---------------------------------------------------------------------------
# Core wiring
# ---------------------------------------------------------------------------
def _mini_core(**kw):
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.si import RegisterResourceManagerRequest
    from yunikorn_tpu.core.scheduler import CoreScheduler

    class CB:
        def predicates(self, a):
            return None

        def __getattr__(self, n):
            return lambda *a, **k: None

    cache = SchedulerCache()
    core = CoreScheduler(cache, **kw)
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="t", policy_group="queues"),
        CB())
    return cache, core


def _add_node(cache, core, name, cpu_milli=8000):
    from yunikorn_tpu.common.objects import make_node
    from yunikorn_tpu.common.si import NodeAction, NodeInfo, NodeRequest

    cache.update_node(make_node(name, cpu_milli=cpu_milli))
    core.update_node(NodeRequest(nodes=[
        NodeInfo(node_id=name, action=NodeAction.CREATE)]))


def _ask_pods(core, names, app="slo-app", cpu=500, priority=0, queue="root.q"):
    from yunikorn_tpu.common.objects import make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import (
        AddApplicationRequest,
        AllocationAsk,
        AllocationRequest,
        ApplicationRequest,
        UserGroupInfo,
    )

    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id=app, queue_name=queue,
        user=UserGroupInfo(user="u"))]))
    pods = [make_pod(n, cpu_milli=cpu, priority=priority) for n in names]
    core.update_allocation(AllocationRequest(asks=[
        AllocationAsk(p.uid, app, get_pod_resource(p), pod=p,
                      priority=priority)
        for p in pods]))
    return pods


def test_core_e2e_tap_and_first_cycle_gauge():
    cache, core = _mini_core()
    _add_node(cache, core, "n0")
    pods = _ask_pods(core, ["sp0", "sp1"])
    assert core.schedule_once() == 2
    assert core._first_cycle_ms is not None
    assert core.obs.get("cold_first_cycle_ms").value() == \
        core._first_cycle_ms
    for p in pods:
        core.observe_pod_bound(p.uid)
    ev = core.slo.tick()
    assert ev["pod_e2e_p99"]["observations"]["fast"] == 2
    assert ev["aot_cold_start"]["value"] == pytest.approx(
        core._first_cycle_ms, abs=0.1)
    # staleness: not running -> objective not applicable
    assert core._slo_staleness() is None
    assert ev["cycle_staleness"]["value"] is None


def test_violated_availability_objective_degrades_health():
    cache, core = _mini_core()
    # force the zero-tolerance availability objective
    core._m_mis_evictions.inc(2)
    core.slo.tick()
    rep = core.health_report()
    assert rep["Healthy"] is True          # liveness untouched (stays 200)
    assert rep["ready"] is False           # readiness degraded
    assert rep["components"]["slo"]["healthy"] is False
    assert rep["components"]["slo"]["violated"] == ["mis_evictions"]
    # /ws/v1/slo serves the same verdicts
    slo = core.slo.report()
    assert "mis_evictions" in slo["violated"] and slo["healthy"] is False


def _victim_cluster(node, n_victims=4):
    """A node saturated by low-priority Running victims, registered with
    BOTH the cache (solver capacity) and the core (releasable allocations)."""
    from yunikorn_tpu.common.objects import make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import (
        AddApplicationRequest,
        Allocation,
        ApplicationRequest,
        UserGroupInfo,
    )

    cache, core = _mini_core()
    _add_node(cache, core, node, cpu_milli=4000)
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="victims-app", queue_name="root.v",
        user=UserGroupInfo(user="v"))]))
    victims = []
    for i in range(n_victims):
        v = make_pod(f"{node}-victim-{i}", cpu_milli=1000, node_name=node,
                     phase="Running", priority=0)
        cache.update_pod(v)
        with core._lock:
            core._restore_allocation(Allocation(
                allocation_key=v.uid, application_id="victims-app",
                node_id=node, resource=get_pod_resource(v), priority=0))
        victims.append(v)
    return cache, core, victims


def _preempting_ask(cache, core, name, app):
    """One high-priority ask that cannot fit without evictions (pod in the
    cache so the victim search resolves it). Returns its allocation key."""
    pods = _ask_pods(core, [name], app=app, cpu=2000, priority=100)
    for p in pods:
        cache.update_pod(p)
    return pods[0].uid


def test_mis_eviction_ledger_counts_only_wasted_evictions():
    # Case A: a high-prio ask preempts, the victims actually terminate, the
    # ask places on the freed room -> the eviction paid off, nothing counts.
    cache, core, victims = _victim_cluster("n1")
    hi = _preempting_ask(cache, core, "mev-hi", "mev-app")
    core.schedule_once()   # unplaced -> preemption plans + evicts
    assert core.obs.get("preempted_total").value() >= 1
    evicted = core._evicted_for.get(hi, 0)
    assert evicted >= 1
    # kubelet terminates the evicted victims (their core allocations were
    # already released by the plan): free the cache capacity too
    for plan in core.recent_preemptions():
        for uid in plan["victims"]:
            v = next(x for x in victims if x.uid == uid)
            cache.remove_pod(v)
    assert core.schedule_once() == 1   # the ask now places
    assert hi not in core._evicted_for
    core._purge_preempt_cooldown(time.time() + 60)
    assert core.obs.get("preemption_mis_evictions_total").value() == 0

    # Case B: evictions happen but the freed room never materializes for
    # the ask (victims keep running in the cache — e.g. stuck terminating);
    # the cooldown expires with the ask still unplaced -> wasted evictions
    cache2, core2, _ = _victim_cluster("n2")
    hi2 = _preempting_ask(cache2, core2, "mev2-hi", "mev2-app")
    core2.schedule_once()
    evicted2 = core2._evicted_for.get(hi2, 0)
    assert evicted2 >= 1
    core2.schedule_once()  # still unplaced (cache capacity never freed)
    assert hi2 in core2._evicted_for
    core2._purge_preempt_cooldown(time.time() + 60)
    m = core2.obs.get("preemption_mis_evictions_total")
    assert m.value() == evicted2
    assert core2.slo.tick()["mis_evictions"]["verdict"] == "violated"


def test_staleness_probe_tracks_run_loop():
    cache, core = _mini_core(interval=0.02)
    _add_node(cache, core, "n0")
    core.start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            ages = core._slo_staleness()
            if ages and ages.get("default", 99) < 0.5:
                break
            time.sleep(0.05)
        ages = core._slo_staleness()
        assert ages is not None and ages["default"] < 2.0
    finally:
        core.stop()
    assert core._slo_staleness() is None


def test_ws_v1_slo_endpoint_serves_report():
    import urllib.request

    from yunikorn_tpu.webapp.rest import RestServer

    cache, core = _mini_core()
    _add_node(cache, core, "n0")
    rest = RestServer(core, None, port=0)
    port = rest.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/ws/v1/slo", timeout=10) as r:
            assert r.status == 200
            rep = json.loads(r.read())
        assert set(rep["objectives"]) == set(OBJECTIVES)
        assert rep["healthy"] is True
        assert rep["windows"]["fast_s"] > 0
    finally:
        rest.stop()


# ---------------------------------------------------------------------------
# Grafana round-14 row + exposition prefix rule
# ---------------------------------------------------------------------------
def test_grafana_dashboard_has_slo_row_and_prefixed_queries():
    path = os.path.join(REPO, "deployments", "grafana-dashboard",
                        "yunikorn-tpu-dashboard.json")
    with open(path) as f:
        dash = json.load(f)
    panels = dash["panels"]
    titles = [p.get("title", "") for p in panels]
    assert any("SLO" in t for t in titles), titles
    slo_exprs = [t.get("expr", "") for p in panels
                 for t in p.get("targets", [])
                 if "slo_" in t.get("expr", "")]
    assert any("yunikorn_slo_burn_rate" in e for e in slo_exprs)
    assert any("yunikorn_slo_violations_total" in e for e in slo_exprs)
    assert any('objective="cycle_staleness"' in e for e in slo_exprs)
    # the round-12 rule, now pinned: EVERY query in the dashboard must
    # address the exposition's yunikorn_ prefix — an unprefixed series
    # name silently renders an empty panel against the real /metrics
    for p in panels:
        for t in p.get("targets", []):
            expr = t.get("expr", "")
            assert "yunikorn_" in expr, (p.get("title"), expr)


# ---------------------------------------------------------------------------
# Trace generator determinism (scripts/trace_replay.py)
# ---------------------------------------------------------------------------
def _import_replay():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import trace_replay

    return trace_replay


@pytest.mark.parametrize("trace", ["diurnal", "gang-storm", "quota-churn",
                                   "drain-upgrade", "restart-storm"])
def test_trace_generator_seeded_determinism(trace):
    tr = _import_replay()
    kw = dict(seed=11, nodes=500, pods=200, tenants=4, duration=20.0)
    ev_a, meta_a = tr.generate_trace(trace, **kw)
    ev_b, meta_b = tr.generate_trace(trace, **kw)
    assert ev_a == ev_b and meta_a == meta_b
    assert ev_a, "empty trace"
    ev_c, _ = tr.generate_trace(trace, **{**kw, "seed": 12})
    kinds = {k for _, k, _ in ev_a}
    assert "pods" in kinds
    if trace in ("gang-storm", "restart-storm"):
        # gang jitter is seeded: a different seed moves the event times
        assert ev_a != ev_c
    if trace == "restart-storm":
        assert "restart" in kinds
    if trace == "quota-churn":
        assert "configmap" in kinds
    if trace == "drain-upgrade":
        assert "drain" in kinds and "add_nodes" in kinds
    created = sum(len(p) for _, k, p in ev_a if k == "pods")
    assert created == meta_a["pods_total"] > 0
    assert meta_a["max_wave"] > 0


def test_trace_generator_rejects_unknown_trace():
    tr = _import_replay()
    with pytest.raises(ValueError):
        tr.generate_trace("nope", seed=1, nodes=10, pods=10, tenants=1,
                          duration=5.0)
