"""Sharded solve over the virtual 8-device CPU mesh: results must match the
single-device solve exactly (same deterministic algorithm, different layout).
"""
import jax
import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.parallel.mesh import make_mesh, solve_sharded
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder


@pytest.fixture(scope="module")
def env():
    cache = SchedulerCache()
    for i in range(48):
        cache.update_node(make_node(f"n{i}", cpu_milli=8000, memory=8 * 2**30,
                                    labels={"zone": f"z{i % 3}"}))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"p{i}", cpu_milli=400 + 100 * (i % 5), memory=2**27) for i in range(300)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p) for p in pods]
    batch = enc.build_batch(asks)
    return enc, batch


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_matches_single_device(env):
    enc, batch = env
    single = solve_batch(batch, enc.nodes, chunk=128)
    mesh = make_mesh()
    sharded = solve_sharded(batch, enc.nodes, mesh, chunk=128)
    a1 = np.asarray(single.assigned)[: batch.num_pods]
    a2 = np.asarray(sharded.assigned)[: batch.num_pods]
    assert (a1 >= 0).all() and (a2 >= 0).all()
    # same algorithm, same data → identical assignments
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(np.asarray(single.free_after), np.asarray(sharded.free_after))


def test_sharded_no_oversubscription(env):
    enc, batch = env
    mesh = make_mesh()
    res = solve_sharded(batch, enc.nodes, mesh, chunk=128)
    free = np.asarray(res.free_after)
    assert (free >= 0).all()


from yunikorn_tpu.client.synthetic import make_rich_constraint_pods as _rich_pods_shared


def _rich_pods(n_plain, n_spread, n_anti, n_hostmask, n_soft):
    return _rich_pods_shared(n_plain, n_spread, n_anti, n_hostmask, n_soft)


def test_sharded_rich_constraints_match_single_device():
    """Locality + host-mask + soft channels + a partition node_mask, sharded
    vs single device: identical assignments (VERDICT r2 weak #3)."""
    cache = SchedulerCache()
    for i in range(64):
        cache.update_node(make_node(f"n{i}", cpu_milli=16000, memory=16 * 2**30,
                                    labels={"zone": f"z{i % 4}",
                                            "kubernetes.io/hostname": f"n{i}"}))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = _rich_pods(200, 48, 24, 24, 24)
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p) for p in pods]
    batch = enc.build_batch(asks)
    assert batch.g_host_mask is not None          # host-mask channel engaged
    assert batch.locality is not None             # locality channel engaged
    node_mask = np.ones((enc.nodes.capacity,), bool)
    node_mask[: enc.nodes.capacity // 8] = False  # restrict like a partition
    single = solve_batch(batch, enc.nodes, chunk=64, node_mask=node_mask)
    sharded = solve_sharded(batch, enc.nodes, make_mesh(), chunk=64,
                            node_mask=node_mask)
    a1 = np.asarray(single.assigned)[: batch.num_pods]
    a2 = np.asarray(sharded.assigned)[: batch.num_pods]
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(np.asarray(single.free_after),
                                  np.asarray(sharded.free_after))
    # the masked-off nodes never received anything
    assert not np.isin(a1[a1 >= 0], np.nonzero(~node_mask)[0]).any()


@pytest.mark.slow  # ~160 s: 18% of the tier-1 wall by itself; the smaller
# sharded-parity cases above keep the contract in tier-1
def test_sharded_production_cycle_at_scale():
    """The FULL CoreScheduler cycle (quota gate → rank → encode → sharded
    solve → commit) over the 8-device CPU mesh at >10k pods with locality +
    host-mask + gang placeholder asks: allocation-for-allocation identical to
    the single-device cycle (VERDICT r2 item 4)."""
    import dataclasses as dc

    from yunikorn_tpu.common.si import (AddApplicationRequest, AllocationAsk as Ask,
                                        AllocationRequest, ApplicationRequest,
                                        NodeAction, NodeInfo, NodeRequest,
                                        RegisterResourceManagerRequest,
                                        UserGroupInfo)
    from yunikorn_tpu.core.scheduler import CoreScheduler, SolverOptions

    class CaptureCB:
        def __init__(self):
            self.allocs = {}

        def update_allocation(self, response):
            for a in response.new:
                # key by pod NAME: uids carry a process-global counter, so
                # the two runs' allocation_keys can never literally match
                self.allocs[a.allocation_key.rsplit("-", 1)[0]] = a.node_id

        def update_application(self, r):
            pass

        def update_node(self, r):
            pass

        def predicates(self, a):
            return None

        def preemption_predicates(self, a):
            return None

        def send_event(self, e):
            pass

        def update_container_scheduling_state(self, r):
            pass

        def get_state_dump(self):
            return "{}"

    def build_pods():
        pods = _rich_pods(10_000, 96, 48, 48, 64)
        gang = []
        for i in range(64):
            p = make_pod(f"ph{i}", cpu_milli=300, memory=2**26)
            gang.append((p, True))
        return [(p, False) for p in pods] + gang

    def run(shard: bool):
        cache = SchedulerCache()
        core = CoreScheduler(cache, solver_options=SolverOptions(shard=shard))
        cb = CaptureCB()
        core.register_resource_manager(
            RegisterResourceManagerRequest(rm_id="t", policy_group="queues"), cb)
        infos = []
        for i in range(1024):
            n = make_node(f"n{i}", cpu_milli=16000, memory=32 * 2**30,
                          labels={"zone": f"z{i % 4}",
                                  "kubernetes.io/hostname": f"n{i}"})
            cache.update_node(n)
            infos.append(NodeInfo(node_id=n.name, action=NodeAction.CREATE))
        core.update_node(NodeRequest(nodes=infos))
        core.update_application(ApplicationRequest(new=[AddApplicationRequest(
            application_id="app", queue_name="root.default",
            user=UserGroupInfo(user="u"))]))
        asks = [Ask(p.uid, "app", get_pod_resource(p), pod=p,
                    placeholder=ph, task_group_name="tg" if ph else "")
                for p, ph in build_pods()]
        core.update_allocation(AllocationRequest(asks=asks))
        n = core.schedule_once()
        return n, cb.allocs

    n_single, allocs_single = run(shard=False)
    n_sharded, allocs_sharded = run(shard=True)
    assert n_single == n_sharded
    assert n_single > 10_000          # the mix mostly schedules
    assert allocs_single == allocs_sharded


def test_sharded_with_constraints(env):
    enc, _ = env
    pods = []
    for i in range(40):
        p = make_pod(f"zp{i}", cpu_milli=500, memory=2**26)
        p.spec.node_selector = {"zone": "z1"}
        pods.append(p)
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p) for p in pods]
    batch = enc.build_batch(asks)
    res = solve_sharded(batch, enc.nodes, make_mesh(), chunk=64)
    assigned = np.asarray(res.assigned)[: batch.num_pods]
    assert (assigned >= 0).all()
    for idx in assigned:
        name = enc.nodes.name_of(int(idx))
        assert int(name[1:]) % 3 == 1  # zone z1 nodes only


def test_sharded_chunked_matches_single_chunked(env):
    """Chained chunk solves (max_batch < N) must be bit-identical between the
    sharded and single-device paths — the chunk chaining (capacity carry,
    locality-count carry) is layout-independent."""
    enc, batch = env
    single = solve_batch(batch, enc.nodes, chunk=128, max_batch=128)
    mesh = make_mesh()
    sharded = solve_sharded(batch, enc.nodes, mesh, chunk=128, max_batch=128)
    a1 = np.asarray(single.assigned)[: batch.num_pods]
    a2 = np.asarray(sharded.assigned)[: batch.num_pods]
    assert (a1 >= 0).all()
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(np.asarray(single.free_after),
                                  np.asarray(sharded.free_after))


def test_usage_fold_sharded_matches_single_device():
    """The ledger-mirror fleet fold: the psum-style sharded reduction must
    equal the single-device fold bit-for-bit (int64 end-to-end — exactness
    is the whole point of the device usage mirror)."""
    from jax.experimental import enable_x64

    from yunikorn_tpu.ops.gate_solve import usage_fold
    from yunikorn_tpu.parallel.mesh import usage_fold_sharded

    rng = np.random.default_rng(7)
    host = rng.integers(0, 2**40, size=(8, 16, 4)).astype(np.int64)
    with enable_x64():
        import jax.numpy as jnp

        usage = jnp.asarray(host)
        single = np.asarray(usage_fold(usage))
        folded = np.asarray(usage_fold_sharded(usage, make_mesh()))
    np.testing.assert_array_equal(single, host.sum(axis=0))
    np.testing.assert_array_equal(single, folded)
