"""Sharded solve over the virtual 8-device CPU mesh: results must match the
single-device solve exactly (same deterministic algorithm, different layout).
"""
import jax
import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.parallel.mesh import make_mesh, solve_sharded
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder


@pytest.fixture(scope="module")
def env():
    cache = SchedulerCache()
    for i in range(48):
        cache.update_node(make_node(f"n{i}", cpu_milli=8000, memory=8 * 2**30,
                                    labels={"zone": f"z{i % 3}"}))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"p{i}", cpu_milli=400 + 100 * (i % 5), memory=2**27) for i in range(300)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p) for p in pods]
    batch = enc.build_batch(asks)
    return enc, batch


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_matches_single_device(env):
    enc, batch = env
    single = solve_batch(batch, enc.nodes, chunk=128)
    mesh = make_mesh()
    sharded = solve_sharded(batch, enc.nodes, mesh, chunk=128)
    a1 = np.asarray(single.assigned)[: batch.num_pods]
    a2 = np.asarray(sharded.assigned)[: batch.num_pods]
    assert (a1 >= 0).all() and (a2 >= 0).all()
    # same algorithm, same data → identical assignments
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(np.asarray(single.free_after), np.asarray(sharded.free_after))


def test_sharded_no_oversubscription(env):
    enc, batch = env
    mesh = make_mesh()
    res = solve_sharded(batch, enc.nodes, mesh, chunk=128)
    free = np.asarray(res.free_after)
    assert (free >= 0).all()


def test_sharded_with_constraints(env):
    enc, _ = env
    pods = []
    for i in range(40):
        p = make_pod(f"zp{i}", cpu_milli=500, memory=2**26)
        p.spec.node_selector = {"zone": "z1"}
        pods.append(p)
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p) for p in pods]
    batch = enc.build_batch(asks)
    res = solve_sharded(batch, enc.nodes, make_mesh(), chunk=64)
    assigned = np.asarray(res.assigned)[: batch.num_pods]
    assert (assigned >= 0).all()
    for idx in assigned:
        name = enc.nodes.name_of(int(idx))
        assert int(name[1:]) % 3 == 1  # zone z1 nodes only
