"""Solver chaos suite: faults injected into every supervised device path.

The differential guarantee (ISSUE 4): with faults injected — fail-fast,
hang-past-deadline, fail-N-then-recover, permanent failure — placements are
identical to a fault-free `schedule_once` run on the same event trace, the
circuit re-closes after the fault clears (a recovered TPU is reclaimed
without restart), `/ws/v1/health` reflects each transition, and a permanent
device failure leaves the scheduler live and placing pods via the host
tier, never stalled.

Driven through the injectable fault plane (robustness/faults.py): rules are
consumed inside the supervised attempt on the watchdog worker, so a
scripted `slow` really trips the dispatch deadline the way a wedged XLA
dispatch would.
"""
import json
import time
import urllib.error
import urllib.request

import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import (
    AddApplicationRequest,
    AllocationAsk,
    AllocationRequest,
    ApplicationRequest,
    NodeAction,
    NodeInfo,
    NodeRequest,
    RegisterResourceManagerRequest,
    UserGroupInfo,
)
from yunikorn_tpu.core.scheduler import CoreScheduler, SolverOptions
from yunikorn_tpu.robustness.supervisor import (
    AllTiersFailed,
    SupervisorOptions,
)


class NullCallback:
    def __getattr__(self, name):
        return lambda *a, **k: None


FAST = SupervisorOptions(deadline_s=30.0, max_retries=2, backoff_base_s=0.005,
                         breaker_threshold=2, probe_interval_s=0.2)


def make_core(n_nodes=32, options=None, pipeline=False, shard=None,
              config="", **solver_kwargs):
    cache = SchedulerCache()
    core = CoreScheduler(
        cache,
        solver_options=SolverOptions(pipeline=pipeline, shard=shard,
                                     **solver_kwargs),
        supervisor_options=options or dataclasses_replace(FAST))
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="chaos", policy_group="queues",
                                       config=config),
        NullCallback())
    nodes = make_kwok_nodes(n_nodes)
    for n in nodes:
        cache.update_node(n)
    core.update_node(NodeRequest(nodes=[
        NodeInfo(node_id=n.name, action=NodeAction.CREATE) for n in nodes]))
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="app", queue_name="root.q",
        user=UserGroupInfo(user="u"))]))
    return cache, core


def dataclasses_replace(opts):
    import dataclasses

    return dataclasses.replace(opts)


def asks_of(pods):
    return [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p)
            for p in pods]


def placements_by_name(core, uid_to_name):
    out = {}
    for app in core.partition.applications.values():
        for key, alloc in app.allocations.items():
            out[uid_to_name[key]] = alloc.node_id
    return out


def run_trace(core, waves, names):
    for pods in waves:
        names.update({p.uid: p.name for p in pods})
        core.update_allocation(AllocationRequest(asks=asks_of(pods)))
        core.schedule_once()
    return placements_by_name(core, names)


def two_waves(cpu_milli=100):
    return [make_sleep_pods(60, "app", queue="root.q", name_prefix="c1",
                            cpu_milli=cpu_milli),
            make_sleep_pods(60, "app", queue="root.q", name_prefix="c2",
                            cpu_milli=cpu_milli)]


def clean_placements(cpu_milli=100):
    cache, core = make_core()
    names = {}
    return run_trace(core, two_waves(cpu_milli), names)


def outcome(core, path, kind):
    c = core.obs.get("supervised_dispatch_total")
    # aggregate over the policy label (greedy/optimal) — these tests care
    # about path outcomes, not which solver policy the cycle ran
    return c.sum_over(path=path, outcome=kind) if c is not None else 0.0


# ---------------------------------------------------------------- fail fast
def test_transient_fault_retries_and_matches_fault_free():
    """A transient dispatch failure retries the same tier: placements stay
    identical to the fault-free run and the circuit never opens."""
    cache, core = make_core()
    core.supervisor.faults.fail("assign", times=1, tier="device")
    names = {}
    got = run_trace(core, two_waves(), names)
    assert got == clean_placements()
    assert len(got) == 120
    assert outcome(core, "assign", "transient") >= 1
    snap = core.supervisor.snapshot()
    assert snap["assign"]["tier"] == "device"
    assert snap["assign"]["circuits"]["device"]["state"] == "closed"
    assert core.supervisor.degradations() == []


def test_persistent_fault_degrades_immediately_and_matches():
    """A persistent (compile/shape-class) failure skips the same-tier retry,
    opens the circuit, and the CPU re-jit tier answers with identical
    placements."""
    cache, core = make_core()
    core.supervisor.faults.fail("assign", times=10, tier="device",
                                persistent=True)
    names = {}
    got = run_trace(core, two_waves(), names)
    assert got == clean_placements()
    snap = core.supervisor.snapshot()
    assert snap["assign"]["circuits"]["device"]["state"] == "open"
    assert snap["assign"]["tier"] == "cpu"
    g = core.obs.get("solver_degradation_state")
    assert g.value(path="assign") == 1.0
    events = [d["event"] for d in core.supervisor.degradations()]
    assert "degrade" in events


# ---------------------------------------------------- hang past the deadline
def test_hang_past_deadline_degrades_and_matches():
    """A dispatch that sleeps past the deadline is abandoned by the watchdog
    (the wedged-relay failure mode) and the cycle completes on the next tier
    with identical placements — the scheduler never stalls."""
    opts = dataclasses_replace(FAST)
    opts.deadline_s = 0.25
    cache, core = make_core(options=opts)
    core.supervisor.faults.slow("assign", seconds=2.0, times=100,
                                tier="device")
    names = {}
    t0 = time.time()
    got = run_trace(core, two_waves(), names)
    wall = time.time() - t0
    assert got == clean_placements()
    assert outcome(core, "assign", "deadline") >= 1
    # two cycles x (one deadline each + fallback solve): a wedged dispatch
    # costs its deadline, never the whole budget
    assert wall < 20, wall
    # consecutive deadline blows opened the device circuit
    assert core.supervisor.snapshot()["assign"]["circuits"]["device"]["state"] == "open"


# ------------------------------------------------- fail N then recover/probe
def test_fail_n_then_recover_circuit_recloses():
    """Failures open the device circuit (degrade to cpu); once the fault
    clears, the half-open probe re-closes it — the recovered backend is
    reclaimed without restart."""
    opts = dataclasses_replace(FAST)
    opts.max_retries = 0
    cache, core = make_core(options=opts)
    core.supervisor.faults.fail("assign", times=4, tier="device")
    names = {}
    got = run_trace(core, two_waves(), names)
    assert got == clean_placements()
    assert core.supervisor.snapshot()["assign"]["circuits"]["device"]["state"] == "open"
    core.supervisor.faults.clear()

    # past the probe interval the next dispatch probes the device tier and
    # its materialized success re-closes the circuit
    time.sleep(opts.probe_interval_s + 0.05)
    extra = make_sleep_pods(10, "app", queue="root.q", name_prefix="rec")
    names.update({p.uid: p.name for p in extra})
    core.update_allocation(AllocationRequest(asks=asks_of(extra)))
    core.schedule_once()
    snap = core.supervisor.snapshot()
    assert snap["assign"]["circuits"]["device"]["state"] == "closed"
    assert snap["assign"]["tier"] == "device"
    events = [d["event"] for d in core.supervisor.degradations()]
    assert events.count("degrade") >= 1 and events.count("recover") >= 1
    g = core.obs.get("solver_degradation_state")
    assert g.value(path="assign") == 0.0
    assert len(placements_by_name(core, names)) == 130


# --------------------------------------------- permanent failure → host tier
def test_permanent_device_failure_places_via_host_tier():
    """Device AND cpu tiers permanently down: the scheduler keeps placing
    pods through the exact host path, with placements identical to the
    fault-free device run (homogeneous batch: the host greedy reproduces
    the device water-fill exactly)."""
    opts = dataclasses_replace(FAST)
    opts.breaker_threshold = 1
    opts.max_retries = 0
    opts.probe_interval_s = 60.0  # keep the circuits open for the test
    cache, core = make_core(options=opts)
    core.supervisor.faults.fail_forever("assign", tier="device")
    core.supervisor.faults.fail_forever("assign", tier="cpu")
    names = {}
    # 4-core pods over 32-core nodes: the batch spans many nodes, so the
    # equivalence check exercises the host water-fill across node boundaries
    got = run_trace(core, two_waves(cpu_milli=4000), names)
    assert got == clean_placements(cpu_milli=4000)
    assert len(got) == 120
    snap = core.supervisor.snapshot()
    assert snap["assign"]["tier"] == "host"
    g = core.obs.get("solver_degradation_state")
    assert g.value(path="assign") == 2.0
    # still live and still placing: a third wave lands through the host tier
    extra = make_sleep_pods(20, "app", queue="root.q", name_prefix="c3",
                            cpu_milli=4000)
    names.update({p.uid: p.name for p in extra})
    core.update_allocation(AllocationRequest(asks=asks_of(extra)))
    core.schedule_once()
    placed = placements_by_name(core, names)
    assert len(placed) == 140
    report = core.health_report()
    assert report["Healthy"] is True  # degraded != dead
    assert report["components"]["solver"]["state"] == "degraded"
    assert report["components"]["solver"]["degraded"] == {"assign": "host"}


# ------------------------------------------------------------- upload faults
def test_host_tier_honors_anti_affinity():
    """Device AND cpu tiers down: the host tier must enforce the
    placement-dependent predicates the device solve encodes — required pod
    anti-affinity pods land on distinct nodes, never stacked on the
    binpacking winner."""
    from yunikorn_tpu.common.objects import Affinity, PodAffinityTerm

    def anti_wave():
        pods = make_sleep_pods(4, "app", queue="root.q", name_prefix="anti",
                               extra_labels={"app": "singleton"})
        for p in pods:
            p.spec.affinity = Affinity(pod_anti_affinity_required=[
                PodAffinityTerm(
                    label_selector={"matchLabels": {"app": "singleton"}},
                    topology_key="kubernetes.io/hostname")])
        return pods

    opts = dataclasses_replace(FAST)
    opts.breaker_threshold = 1
    opts.max_retries = 0
    opts.probe_interval_s = 60.0
    cache, core = make_core(options=opts)
    core.supervisor.faults.fail_forever("assign", tier="device")
    core.supervisor.faults.fail_forever("assign", tier="cpu")
    names = {}
    got = run_trace(core, [anti_wave()], names)
    assert core.supervisor.snapshot()["assign"]["tier"] == "host"
    assert len(got) == 4
    assert len(set(got.values())) == 4  # one per node

    clean_cache, clean_core = make_core()
    clean = run_trace(clean_core, [anti_wave()], {})
    assert got == clean


def test_host_tier_honors_inflight_ports():
    """The host tier must see the same committed-but-not-assumed hostPort
    overlay the device tiers receive as ports_delta — without it, two
    consecutive degraded cycles could each place a pod wanting the same
    hostPort on the binpacking-winner node."""
    import numpy as np

    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.robustness.host_solve import host_assign
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder
    from yunikorn_tpu.snapshot.vocab import port_bit

    cache = SchedulerCache()
    for i in range(2):
        cache.update_node(make_node(f"pn{i}", cpu_milli=8000))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pod = make_pod("port-pod", cpu_milli=100, memory=2**20)
    pod.spec.containers[0].ports = [{"hostPort": 8080, "protocol": "TCP"}]
    ask = AllocationAsk(pod.uid, "app", get_pod_resource(pod), pod=pod)
    batch = enc.build_batch([ask])

    # no overlay: binpacking picks the lowest-index node
    free_row = int(host_assign([ask], batch, enc, cache)[0])
    assert free_row >= 0
    # overlay says that node already holds 8080 from an in-flight commit
    b = enc.vocabs.ports.lookup(port_bit("TCP", 8080))
    assert b >= 0
    ports_delta = np.zeros((enc.nodes.capacity, enc.vocabs.ports.num_words),
                           np.uint32)
    ports_delta[free_row, b // 32] |= np.uint32(1 << (b % 32))
    got = int(host_assign([ask], batch, enc, cache,
                          ports_delta=ports_delta)[0])
    assert got >= 0 and got != free_row


def test_single_tier_fallback_gauge_value():
    """A single-tier path degraded to its external fallback reports the
    dedicated gauge value (3=external fallback), not the assign ladder's
    cpu slot (1) — an operator must not read 'cpu re-jit' on a path that
    has no such tier."""
    from yunikorn_tpu.obs.metrics import MetricsRegistry
    from yunikorn_tpu.robustness.supervisor import (
        FALLBACK_TIER,
        SupervisedExecutor,
    )

    reg = MetricsRegistry()
    ex = SupervisedExecutor(SupervisorOptions(
        deadline_s=5.0, max_retries=0, breaker_threshold=1,
        probe_interval_s=60.0), registry=reg)

    def boom():
        raise ValueError("shape mismatch")  # persistent class: opens now

    with pytest.raises(ValueError):
        ex.run("upload", boom)
    assert ex.current_tier("upload") == FALLBACK_TIER
    g = reg.get("solver_degradation_state")
    assert g.value(path="upload") == 3.0


def test_failed_upload_falls_back_to_per_cycle_transfer():
    """A failing device-mirror upload opens the upload circuit; the solve
    proceeds with per-cycle uploads, and the probe re-closes the circuit
    after the fault clears."""
    opts = dataclasses_replace(FAST)
    opts.breaker_threshold = 1
    opts.max_retries = 0
    cache, core = make_core(options=opts)
    core.supervisor.faults.fail("upload", times=1)
    names = {}
    got = run_trace(core, two_waves(), names)
    assert got == clean_placements()
    assert outcome(core, "upload", "transient") >= 1
    assert outcome(core, "assign", "ok") >= 1
    # fault cleared after one firing; past the probe interval the upload
    # path recovers on the next cycle
    time.sleep(opts.probe_interval_s + 0.05)
    extra = make_sleep_pods(5, "app", queue="root.q", name_prefix="up")
    names.update({p.uid: p.name for p in extra})
    core.update_allocation(AllocationRequest(asks=asks_of(extra)))
    core.schedule_once()
    assert core.supervisor.snapshot()["upload"]["circuits"]["device"]["state"] == "closed"


def test_deadline_abandoned_upload_orphans_device_mirror():
    """A deadline-abandoned dispatch leaves its watchdog thread RUNNING; the
    device mirror it may still be mutating must be orphaned — replaced
    object, epoch bump — so the zombie's late writes land on an
    unreferenced object instead of tearing the next cycle's refresh."""
    import dataclasses

    cache, core = make_core()
    names = {}
    w1 = make_sleep_pods(30, "app", queue="root.q", name_prefix="ob1")
    names.update({p.uid: p.name for p in w1})
    core.update_allocation(AllocationRequest(asks=asks_of(w1)))
    core.schedule_once()                       # warm: compiles, builds mirror
    dev0 = core.encoder.device
    assert dev0 is not None and not dev0.dead
    epoch0 = core.encoder.mirror_epoch
    # tighten the deadline only now (the warm-up compile stays unaffected),
    # then wedge the next mirror refresh past it
    core.supervisor.options = dataclasses.replace(
        core.supervisor.options, deadline_s=0.3, max_retries=0,
        breaker_threshold=100)
    core.supervisor.faults.slow("upload", seconds=1.2, times=1)
    w2 = make_sleep_pods(30, "app", queue="root.q", name_prefix="ob2")
    names.update({p.uid: p.name for p in w2})
    core.update_allocation(AllocationRequest(asks=asks_of(w2)))
    core.schedule_once()
    # the upload nests inside the assign dispatch and both share the
    # deadline, so the abandonment lands on one or both paths
    assert (outcome(core, "upload", "deadline") >= 1
            or outcome(core, "assign", "deadline") >= 1)
    assert dev0.dead is True                   # orphaned...
    assert core.encoder.device is not dev0     # ...and replaced
    assert core.encoder.mirror_epoch > epoch0
    # the cycle itself still placed everything (per-cycle transfer fallback)
    assert len(placements_by_name(core, names)) == 60
    # let the zombie unwedge: it must bail on the stale epoch, and a later
    # cycle rebuilds a LIVE mirror from scratch
    time.sleep(1.3)
    w3 = make_sleep_pods(5, "app", queue="root.q", name_prefix="ob3")
    names.update({p.uid: p.name for p in w3})
    core.update_allocation(AllocationRequest(asks=asks_of(w3)))
    core.schedule_once()
    assert core.encoder.device is not None
    assert core.encoder.device is not dev0
    assert not core.encoder.device.dead
    assert len(placements_by_name(core, names)) == 65


def test_abandoned_thread_nested_dispatch_bails():
    """A watchdog thread abandoned by its waiter is a zombie: its NESTED
    supervised calls must raise instead of running, and none of its
    outcomes may move live circuits or metrics."""
    from yunikorn_tpu.robustness.supervisor import (
        AbandonedDispatch,
        DeadlineExceeded,
        SupervisedExecutor,
    )

    ex = SupervisedExecutor(SupervisorOptions(
        deadline_s=0.1, max_retries=0, breaker_threshold=1,
        probe_interval_s=60.0))
    seen = {}

    def outer():
        time.sleep(0.4)                        # outlives the deadline
        seen["allow"] = ex.allow("inner")      # zombie gate: must refuse
        try:
            ex.run("inner", lambda: "never")
        except AbandonedDispatch:
            seen["bailed"] = True
        return "late"

    with pytest.raises(DeadlineExceeded):
        ex.run("outer", outer)
    deadline = time.time() + 5
    while "bailed" not in seen and time.time() < deadline:
        time.sleep(0.02)
    assert seen.get("bailed") is True
    assert seen.get("allow") is False          # allow() refuses zombies too
    assert "inner" not in ex.snapshot()        # never registered, never moved


def test_open_mesh_circuit_drops_to_unsharded_mirror():
    """With the mesh circuit open the cycle must take the single-device
    shape up front: the mirror refreshes UNSHARDED and the fallback solve
    reuses it, instead of paying a sharded upload the skipped mesh dispatch
    would discard plus a full per-cycle transfer."""
    opts = dataclasses_replace(FAST)
    opts.breaker_threshold = 1
    opts.max_retries = 0
    opts.probe_interval_s = 3600.0             # keep the circuit open
    cache, core = make_core(options=opts, shard=True)
    core.supervisor.faults.fail("mesh", times=1)
    names = {}
    got = run_trace(core, two_waves(), names)  # wave 1 opens the circuit
    assert got == clean_placements()
    assert len(got) == 120
    assert core.supervisor.snapshot()["mesh"]["circuits"]["device"]["state"] == "open"
    dev = core.encoder.device
    assert dev is not None
    # wave 2 ran degraded: the live mirror must be committed unsharded so
    # the single-device solve could reuse it (no double transfer)
    assert dev._mesh is None


# -------------------------------------------------------- preemption faults
def preemption_core(options):
    """Full node + one evictable low-priority victim per node, then a
    high-priority ask that can only place by preempting."""
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.si import Allocation

    cache = SchedulerCache()
    victims = []
    for i in range(2):
        cache.update_node(make_node(f"pn{i}", cpu_milli=2000,
                                    memory=8 * 2**30))
        v = make_pod(f"pv-{i}", cpu_milli=2000, memory=2**28,
                     node_name=f"pn{i}", phase="Running", priority=0)
        cache.update_pod(v)
        victims.append(v)
    core = CoreScheduler(cache, solver_options=SolverOptions(pipeline=False),
                         supervisor_options=options)
    released = []

    class Callback(NullCallback):
        def update_allocation(self, response):
            for rel in getattr(response, "released", []):
                released.append(rel.allocation_key)

    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="p", policy_group="queues"),
        Callback())
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="victim-app",
                              queue_name="root.qv",
                              user=UserGroupInfo(user="v")),
        AddApplicationRequest(application_id="hi-app", queue_name="root.qhi",
                              user=UserGroupInfo(user="h"))]))
    infos = [NodeInfo(node_id=f"pn{i}", action=NodeAction.CREATE,
                      existing_allocations=[Allocation(
                          allocation_key=v.uid, application_id="victim-app",
                          node_id=f"pn{i}",
                          resource=get_pod_resource(v))])
             for i, v in enumerate(victims)]
    core.update_node(NodeRequest(nodes=infos))
    return cache, core, released


def test_preempt_device_fault_host_planner_covers():
    """A failing device preemption solve opens the preempt circuit and the
    host planner covers the cycle: the victim is still evicted."""
    from yunikorn_tpu.common.objects import make_pod

    opts = dataclasses_replace(FAST)
    opts.breaker_threshold = 1
    opts.max_retries = 0
    opts.probe_interval_s = 60.0
    cache, core, released = preemption_core(opts)
    core.supervisor.faults.fail_forever("preempt")
    hp = make_pod("hi-pod", cpu_milli=2000, memory=2**28, priority=100)
    cache.update_pod(hp)
    core.update_allocation(AllocationRequest(asks=[AllocationAsk(
        hp.uid, "hi-app", get_pod_resource(hp), priority=100, pod=hp)]))
    core.schedule_once()
    assert released, "host planner did not evict under a preempt-path fault"
    plans = core.obs.get("preemption_plans_total")
    assert plans.value(planner="host") >= 1
    assert core.supervisor.snapshot()["preempt"]["circuits"]["device"]["state"] == "open"


# --------------------------------------------------------- health endpoint
def test_health_endpoint_reflects_transitions():
    """/ws/v1/health: 200 + per-component detail when healthy; solver
    degradation visible while circuits are open; 503 when every tier of a
    path is unserviceable; recovery restores 200 and the device tier."""
    from yunikorn_tpu.webapp.rest import RestServer

    opts = dataclasses_replace(FAST)
    opts.breaker_threshold = 1
    opts.max_retries = 0
    opts.probe_interval_s = 0.3
    cache, core = make_core(options=opts)
    rest = RestServer(core, None, port=0)
    port = rest.start()

    def health():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/ws/v1/health", timeout=10) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        names = {}
        pods = make_sleep_pods(10, "app", queue="root.q", name_prefix="h1")
        names.update({p.uid: p.name for p in pods})
        core.update_allocation(AllocationRequest(asks=asks_of(pods)))
        core.schedule_once()
        code, rep = health()
        assert code == 200 and rep["Healthy"] is True and rep["ready"] is True
        assert rep["components"]["solver"]["state"] == "ok"
        assert "scheduling" in rep["components"]

        # every tier down → the next cycle fails entirely → unserviceable
        core.supervisor.faults.fail_forever("assign")
        pods = make_sleep_pods(5, "app", queue="root.q", name_prefix="h2")
        names.update({p.uid: p.name for p in pods})
        core.update_allocation(AllocationRequest(asks=asks_of(pods)))
        with pytest.raises(AllTiersFailed):
            core.schedule_once()
        code, rep = health()
        assert code == 503 and rep["Healthy"] is False
        assert rep["components"]["solver"]["state"] == "unserviceable"
        assert "assign" in rep["components"]["solver"]["unserviceable"]
        assert rep["components"]["scheduling"]["last_failure"]["stage"]

        # fault clears; past the probe interval the probe dispatch re-closes
        # the device circuit and health returns to 200/ok
        core.supervisor.faults.clear()
        time.sleep(opts.probe_interval_s + 0.05)
        core.schedule_once()
        code, rep = health()
        assert code == 200 and rep["Healthy"] is True
        assert rep["components"]["solver"]["state"] == "ok"
        assert core.supervisor.snapshot()["assign"]["tier"] == "device"
        assert len(placements_by_name(core, names)) == 15
    finally:
        rest.stop()


def test_cycle_failures_counted_by_stage():
    """Satellite: core/scheduler cycle failures are counted (stage label)
    and surfaced in the health report instead of only swallowed into the
    log — driven through the run loop so the except path itself is tested."""
    opts = dataclasses_replace(FAST)
    opts.breaker_threshold = 1
    opts.max_retries = 0
    opts.probe_interval_s = 60.0
    cache, core = make_core(options=opts)
    core.supervisor.faults.fail_forever("assign")
    pods = make_sleep_pods(5, "app", queue="root.q", name_prefix="cf")
    core.update_allocation(AllocationRequest(asks=asks_of(pods)))
    core.start()
    try:
        deadline = time.time() + 10
        c = core.obs.get("scheduling_cycle_failures_total")
        while time.time() < deadline:
            if sum(v for _, _, v in c.collect()) >= 1:
                break
            time.sleep(0.05)
        total = {labels: v for _, labels, v in c.collect()}
        assert sum(total.values()) >= 1, total
    finally:
        core.stop()
    assert core._last_cycle_failure is not None
    rep = core.health_report()
    assert "last_failure" in rep["components"]["scheduling"]


# ------------------------------------------------------- dispatcher drops
def test_dispatcher_deadline_drop_is_counted(monkeypatch):
    """Satellite: an overflow event whose dispatch timeout expires before
    buffer space frees is DROPPED — the drop must be counted
    (dispatch_dropped_total), not only logged."""
    import threading

    from yunikorn_tpu.common.events import SchedulingEvent
    from yunikorn_tpu.dispatcher import dispatcher as dmod
    from yunikorn_tpu.obs.metrics import MetricsRegistry

    monkeypatch.setattr(dmod, "ASYNC_RETRY_INTERVAL", 0.05)
    d = dmod.Dispatcher(capacity=4, dispatch_timeout=0.15)
    reg = MetricsRegistry()
    d.attach_metrics(reg)
    gate = threading.Event()
    first = threading.Event()

    def handler(event):
        first.set()
        gate.wait(timeout=30)

    d.register_event_handler("blocker", dmod.EventType.SCHEDULER, handler)
    d.start()
    try:
        d.dispatch(SchedulingEvent())          # consumer grabs it and blocks
        assert first.wait(timeout=5)
        for _ in range(4):                     # fill the buffer to capacity
            d.dispatch(SchedulingEvent())
        overflowed = [SchedulingEvent() for _ in range(3)]
        for e in overflowed:                   # past capacity → retry worker
            d.dispatch(SchedulingEvent())
        assert reg.get("dispatcher_overflow_total").value() >= 3
        # the consumer stays blocked, so buffer space never frees and the
        # overflow events' deadlines (0.15s) expire → counted drops
        deadline = time.time() + 10
        dropped = reg.get("dispatch_dropped_total")
        while time.time() < deadline and dropped.value() < 3:
            time.sleep(0.05)
        assert dropped.value() >= 3, dropped.value()
        assert d.dropped_count >= 3
    finally:
        gate.set()
        d.stop()


# ------------------------------------------------ gate degradation ladder
# The device-resident admission gate (ops/gate_solve.py) runs through the
# same supervisor as the solve, on its own "gate" path with the ladder
# device → cpu (host vectorized scan) → host (legacy per-ask loop). The
# differential guarantee mirrors the assign-path suite above: any faulted
# tier degrades with PLACEMENT-identical results (all three gate backends
# are pinned bit-identical), the circuit re-closes once the fault clears,
# and a wedged device gate can never stall the loop.

GATE_YAML = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: q
            resources:
              max: {vcore: 10, memory: 100Gi}
"""


def gate_clean_placements():
    """Fault-free reference run on the quota-constrained trace: the gate
    actively holds asks (demand 12 vcore > 10 vcore max), so gate-path
    equivalence is visible in WHICH pods place, not just how many."""
    cache, core = make_core(config=GATE_YAML)
    names = {}
    return run_trace(core, two_waves(), names)


def test_gate_device_fault_degrades_to_vector_and_matches():
    """A persistently failing device gate degrades to the host vectorized
    tier with identical admissions/placements; the gate circuit opens."""
    cache, core = make_core(config=GATE_YAML)
    core.supervisor.faults.fail("gate", times=10, tier="device",
                                persistent=True)
    names = {}
    got = run_trace(core, two_waves(), names)
    assert got == gate_clean_placements()
    snap = core.supervisor.snapshot()
    assert snap["gate"]["circuits"]["device"]["state"] == "open"
    assert snap["gate"]["tier"] == "cpu"
    assert core.obs.get("gate_path_total").value(path="vector") >= 1
    assert core.obs.get("gate_path_total").value(path="device") == 0
    g = core.obs.get("solver_degradation_state")
    assert g.value(path="gate") == 1.0


def test_gate_all_array_tiers_down_legacy_answers():
    """Device AND host-vectorized tiers down: the legacy per-ask loop still
    decides every cycle, placements unchanged — the gate ladder's bottom
    tier is the exact reference semantics."""
    opts = dataclasses_replace(FAST)
    opts.breaker_threshold = 1
    opts.max_retries = 0
    opts.probe_interval_s = 60.0
    cache, core = make_core(options=opts, config=GATE_YAML)
    core.supervisor.faults.fail_forever("gate", tier="device")
    core.supervisor.faults.fail_forever("gate", tier="cpu")
    names = {}
    got = run_trace(core, two_waves(), names)
    assert got == gate_clean_placements()
    snap = core.supervisor.snapshot()
    assert snap["gate"]["tier"] == "host"
    assert core.obs.get("gate_path_total").value(path="legacy") >= 1


def test_gate_hang_past_deadline_degrades_and_matches():
    """A device gate that wedges past the dispatch deadline is abandoned by
    the watchdog and the cycle completes on the host scan — the admission
    path can no longer stall the loop either."""
    opts = dataclasses_replace(FAST)
    opts.deadline_s = 0.25
    cache, core = make_core(options=opts, config=GATE_YAML)
    core.supervisor.faults.slow("gate", seconds=2.0, times=100,
                                tier="device")
    names = {}
    t0 = time.time()
    got = run_trace(core, two_waves(), names)
    wall = time.time() - t0
    assert got == gate_clean_placements()
    assert outcome(core, "gate", "deadline") >= 1
    assert wall < 20, wall
    assert core.supervisor.snapshot()["gate"]["circuits"]["device"]["state"] == "open"


def test_gate_fault_clears_device_tier_recovers():
    """Once the injected gate fault clears, the half-open probe re-closes
    the device circuit and the device scan is reclaimed without restart."""
    opts = dataclasses_replace(FAST)
    opts.max_retries = 0
    cache, core = make_core(options=opts, config=GATE_YAML)
    core.supervisor.faults.fail("gate", times=4, tier="device")
    names = {}
    got = run_trace(core, two_waves(), names)
    assert got == gate_clean_placements()
    assert core.supervisor.snapshot()["gate"]["circuits"]["device"]["state"] == "open"
    core.supervisor.faults.clear()
    time.sleep(opts.probe_interval_s + 0.05)
    extra = make_sleep_pods(5, "app", queue="root.q", name_prefix="grec",
                            cpu_milli=100)
    names.update({p.uid: p.name for p in extra})
    core.update_allocation(AllocationRequest(asks=asks_of(extra)))
    core.schedule_once()
    snap = core.supervisor.snapshot()
    assert snap["gate"]["circuits"]["device"]["state"] == "closed"
    assert snap["gate"]["tier"] == "device"
    assert core.obs.get("gate_path_total").value(path="device") >= 1


def test_encode_row_store_fault_falls_back_to_host_req():
    """A failing device row-store sync (the supervised "encode" path) falls
    back to the host req tensor for the cycle; placements unchanged."""
    cache, core = make_core(config=GATE_YAML)
    core.supervisor.faults.fail("encode", times=20)
    names = {}
    got = run_trace(core, two_waves(), names)
    assert got == gate_clean_placements()


# --------------------------------------------------------------------------
# AOT background compile (aot/): a store miss in background mode must raise
# CompilePending out of the device tier, the ladder serves the cycle from
# cpu/host (placement-identical), and once the compile thread lands the
# executable the half-open probe reclaims the device tier — the cold
# process is degraded for seconds, never wedged on an inline compile.

def _aot_runtime(tmp_path, background=True):
    from yunikorn_tpu import aot

    rt = aot.AotRuntime(aot.AotStore(str(tmp_path)),
                        background_compile=background)
    aot.set_runtime(rt)
    return rt


def test_aot_pending_degrades_then_probe_reclaims_device(tmp_path):
    from yunikorn_tpu import aot

    try:
        rt = _aot_runtime(tmp_path, background=True)
        opts = dataclasses_replace(FAST)
        opts.max_retries = 0
        cache, core = make_core(options=opts)
        names = {}
        got = run_trace(core, two_waves(), names)
        # the cycles placed identically to a fault-free run, served by a
        # lower tier while the background compile ran
        assert got == clean_placements()
        assert rt.stats()["misses"] >= 1
        assert outcome(core, "assign", "persistent") >= 1
        # the background thread lands the executable
        deadline = time.time() + 120
        while time.time() < deadline:
            s = rt.stats()
            if s["pending"] == 0 and s["compiles"] >= 1 and not s["failed"]:
                break
            time.sleep(0.05)
        assert rt.stats()["compiles"] >= 1
        assert rt.stats()["failed"] == 0
        # past the probe interval, the next dispatch probes the device tier,
        # hits the in-memory executable and re-closes the circuit
        time.sleep(opts.probe_interval_s + 0.05)
        extra = make_sleep_pods(5, "app", queue="root.q", name_prefix="rec",
                                cpu_milli=100)
        names.update({p.uid: p.name for p in extra})
        core.update_allocation(AllocationRequest(asks=asks_of(extra)))
        core.schedule_once()
        snap = core.supervisor.snapshot()["assign"]
        assert snap["circuits"]["device"]["state"] == "closed"
        assert snap["tier"] == "device"
        assert rt.stats()["hits"] >= 1
    finally:
        rt = aot.get_runtime()
        if rt is not None:
            rt.flush(timeout=30.0)
        aot.set_runtime(None)


def test_aot_corrupt_store_entry_never_breaks_the_ladder(tmp_path):
    """A corrupt/truncated artifact quarantines and falls through to a
    normal compile — the cycle still places, identically."""
    import os as _os

    from yunikorn_tpu import aot

    try:
        # build a store inline (background off: misses compile in place)
        rt1 = _aot_runtime(tmp_path, background=False)
        cache, core = make_core()
        names = {}
        got = run_trace(core, two_waves(), names)
        assert got == clean_placements()
        rt1.flush(timeout=60.0)
        store = rt1.store
        assert store.entry_count() >= 1
        for name in _os.listdir(store.entries_dir):
            if not name.endswith(".aotx"):
                continue
            fp = _os.path.join(store.entries_dir, name)
            blob = bytearray(open(fp, "rb").read())
            blob[len(blob) // 2] ^= 0xFF
            with open(fp, "wb") as f:
                f.write(bytes(blob))

        # a "fresh process" over the now-corrupt store
        rt2 = aot.AotRuntime(store)
        aot.set_runtime(rt2)
        cache2, core2 = make_core()
        names2 = {}
        got2 = run_trace(core2, two_waves(), names2)
        assert got2 == clean_placements()
        assert store.corrupt_quarantined >= 1
        assert rt2.stats()["loads"] == 0       # nothing loadable survived
        assert rt2.stats()["compiles"] >= 1
        # no supervised failures: the fall-through is invisible to the ladder
        assert outcome(core2, "assign", "persistent") == 0
    finally:
        rt = aot.get_runtime()
        if rt is not None:
            rt.flush(timeout=30.0)
        aot.set_runtime(None)
