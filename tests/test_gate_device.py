"""Differential + transfer-contract suite for the device-resident gate and
the device row store (ops/gate_solve.py, snapshot/encoder.DeviceRowStore).

The device scan must be indistinguishable from the host vectorized scan —
identical admitted set, identical global order, identical held count — and
transitively from the legacy loop, across the same randomized scenario
space that pinned the host scan (tests/test_gate_vectorized.py): random
trees with nested quotas, user/group limits, fences, gang asks, pipelined
seed/exclude traces. Additionally pinned here:

- the pass bound: the jitted scan can never run more than
  ceil(log2(n_pad)) + GATE_PASS_SLACK passes, and a scan that hits the cap
  still returns the exact result via the host finish of the leftovers;
- the exact-int32 fast path and the int64 path decide identically;
- encode_rows quantization is bit-identical to the host quantize chain;
- a churn cycle uploads only changed rows (the O(changed-asks) transfer
  contract), and the gathered req tensor equals batch.req.astype(int32).
"""
import random

import numpy as np
import pytest

from yunikorn_tpu.common.resource import Resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.core import gate as gate_mod
from yunikorn_tpu.core.gate import extract_problem, host_scan, legacy_admit
from yunikorn_tpu.ops import gate_solve

from tests.test_gate_vectorized import (
    CAP,
    E2E_YAML,
    FakeApp,
    _e2e_core,
    _flat_tree,
    _submit,
    meta_for,
    preload_accounting,
    random_seeds,
    random_trace,
    random_tree,
)


def run_three(tree, by_queue, seeds=None):
    """device, host-vectorized and legacy on copies of the same trace."""
    meta = meta_for(tree, by_queue)
    problem = extract_problem({q: list(v) for q, v in by_queue.items()},
                              meta, tree, seeds)
    d_adm, d_held, d_stats = gate_solve.device_admit(problem)
    v_adm, v_held, _ = host_scan(problem)
    l_adm, l_held = legacy_admit({q: list(v) for q, v in by_queue.items()},
                                 meta, tree, seeds)
    return (d_adm, d_held, d_stats), (v_adm, v_held), (l_adm, l_held)


def assert_three_way(tree, by_queue, seeds=None):
    (d_adm, d_held, d_stats), (v_adm, v_held), (l_adm, l_held) = run_three(
        tree, by_queue, seeds)
    keys = [a.allocation_key for a in d_adm]
    assert keys == [a.allocation_key for a in v_adm]
    assert keys == [a.allocation_key for a in l_adm]
    assert d_held == v_held == l_held
    if "max_passes" in d_stats:
        assert d_stats["passes"] <= d_stats["max_passes"]
    return d_stats


# --------------------------------------------------------------- randomized
def test_randomized_trees_differential():
    """60 seeded random (tree, accounting, trace) scenarios — device ==
    host vectorized == legacy exactly, pass bound respected."""
    for seed in range(60):
        rng = random.Random(seed)
        tree = random_tree(rng)
        preload_accounting(rng, tree)
        by_queue = random_trace(rng, tree)
        assert_three_way(tree, by_queue)


def test_randomized_with_seed_admissions():
    """The pipelined gate's in-flight charge (seed_admissions) through the
    device scan: identical to both host paths."""
    for seed in range(40):
        rng = random.Random(1000 + seed)
        tree = random_tree(rng)
        preload_accounting(rng, tree)
        by_queue = random_trace(rng, tree)
        assert_three_way(tree, by_queue, seeds=random_seeds(rng, tree))


def test_pass_cap_leftovers_finish_exact(monkeypatch):
    """With the pass budget strangled to 1, the device scan leaves
    undecided asks; finish_leftovers must complete them to the identical
    result — the no-data-dependent-blowup guarantee's other half."""
    monkeypatch.setattr(gate_solve, "GATE_PASS_SLACK", -7)  # max_passes ~ 1
    saw_leftovers = False
    for seed in range(20):
        rng = random.Random(3000 + seed)
        tree = random_tree(rng)
        preload_accounting(rng, tree)
        by_queue = random_trace(rng, tree)
        stats = assert_three_way(tree, by_queue)
        if stats.get("finish_loop"):
            saw_leftovers = True
    assert saw_leftovers, "pass cap of ~1 never left leftovers — test inert"


def test_int64_wide_values_path():
    """Quantities past the int32 bound (memory in bytes at cluster scale)
    take the int64 kernel; decisions stay pinned."""
    tree = _flat_tree(max_resource=Resource({"memory": 40 * 2**30}))
    app = FakeApp("alice", [], 1.0, "root.q")
    by_queue = {"root.q": [
        (app, AllocationAsk(f"m{i}", "app",
                            Resource({"memory": 8 * 2**30}), seq=i))
        for i in range(8)]}
    stats = assert_three_way(tree, by_queue)
    assert stats["passes"] >= 1


def test_device_matches_on_bench_shapes():
    """The gate_bench trace generator's three contention shapes at a small
    size: the shapes the perf acceptance is judged on stay pinned."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "gate_bench", os.path.join(os.path.dirname(__file__), "..",
                                   "scripts", "gate_bench.py"))
    gb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gb)
    for scale in (1.3, 1.0, 0.2):
        tree = gb.build_tree(2000, scale=scale)
        by_queue = gb.build_trace(tree, 2000)
        stats = assert_three_way(tree, by_queue)
        assert stats["passes"] <= gate_solve.max_passes_for(2000)


# ----------------------------------------------------------- encode / rows
def _mk_ask(i, res, seq=None):
    return AllocationAsk(f"ask-{i}", "app", res, seq=seq if seq is not None
                         else i)


def test_encode_rows_matches_host_quantization():
    """Device quantization (encode_rows) is bit-identical to the host
    SnapshotEncoder.quantize_request chain, including the f32 rounding and
    non-integral values, across random resource shapes."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    enc = SnapshotEncoder(SchedulerCache())
    rng = random.Random(7)
    asks = []
    for i in range(64):
        res = {"cpu": rng.randint(1, 10**6),
               "memory": rng.randint(1, 2**40)}
        if rng.random() < 0.3:
            res["nvidia.com/gpu"] = rng.randint(1, 16)
        if rng.random() < 0.2:
            res["weird"] = rng.random() * 100  # non-integral host fallback
        asks.append(_mk_ask(i, Resource(res)))
    store = enc.device_row_store()
    req = store.sync_and_gather(asks, len(asks))
    got = np.asarray(req)
    for i, ask in enumerate(asks):
        want = np.zeros((store._R,), np.float32)
        row = enc.quantize_request(ask.resource)
        want[: row.shape[0]] = row
        assert np.array_equal(got[i], want.astype(np.int32)), (
            i, ask.resource.resources, got[i], want)


def test_row_store_churn_uploads_only_changed():
    """The O(changed-asks) transfer contract: a 1%-churn second cycle
    uploads exactly the changed rows; an unchanged cycle uploads none."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    enc = SnapshotEncoder(SchedulerCache())
    store = enc.device_row_store()
    asks = [_mk_ask(i, Resource({"cpu": 100 + i % 7})) for i in range(500)]
    req1 = store.sync_and_gather(asks, 512)
    assert store.last_upload_rows == 500
    # identical cycle: zero rows shipped, gather still serves the batch
    req2 = store.sync_and_gather(asks, 512)
    assert store.last_upload_rows == 0
    assert store.last_upload_bytes == 0
    assert np.array_equal(np.asarray(req1), np.asarray(req2))
    # 1% churn: fresh seq + new resource on 5 asks → exactly 5 rows ship
    for i in range(5):
        asks[i] = _mk_ask(i, Resource({"cpu": 9000}), seq=1000 + i)
    req3 = store.sync_and_gather(asks, 512)
    assert store.last_upload_rows == 5
    got = np.asarray(req3)
    assert (got[:5, 0] == 9000).all()
    assert np.array_equal(got[5:500], np.asarray(req1)[5:500])
    # padding rows are the reserved zero slot
    assert (got[500:] == 0).all()


def test_row_store_vocab_growth_resets():
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    enc = SnapshotEncoder(SchedulerCache())
    store = enc.device_row_store()
    store.sync_and_gather([_mk_ask(0, Resource({"cpu": 1}))], 64)
    # intern enough fresh resource names to cross the padded-slot boundary
    for j in range(store._R + 1):
        enc.vocabs.resources.slot(f"vendor.io/dev{j}")
    store.sync_and_gather([_mk_ask(0, Resource({"cpu": 1}))], 64)
    assert store.resets == 1
    assert store.last_upload_rows == 1  # full re-upload of the live batch


def test_device_req_matches_batch_req():
    """The solve-facing contract: the device req gather equals
    batch.req.astype(int32) row for row, padding included."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for i in range(8):
        cache.update_node(make_node(f"n{i}", cpu_milli=64000,
                                    memory=128 * 2**30))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"p{i}", cpu_milli=100 + i, memory=(i + 1) * 2**20)
            for i in range(100)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p, seq=i)
            for i, p in enumerate(pods)]
    batch = enc.build_batch(asks)
    req_dev = enc.device_req(asks, batch)
    assert req_dev is not None
    assert np.array_equal(np.asarray(req_dev), batch.req.astype(np.int32))


# ------------------------------------------------------------- end to end
def test_e2e_device_verify_sequential():
    """Full scheduler with the device gate as primary tier, verify mode on:
    the legacy oracle re-runs after every device gate; mismatch pins 0."""
    from yunikorn_tpu.common.objects import make_pod

    cache, core = _e2e_core(E2E_YAML, gate_device=True)
    _submit(core, "appa", "root.qa", "ua",
            [make_pod(f"da-{i}", cpu_milli=1000, memory="512Mi")
             for i in range(12)])
    _submit(core, "appb", "root.qb", "ub",
            [make_pod(f"db-{i}", cpu_milli=500, memory="256Mi")
             for i in range(8)])
    for _ in range(3):
        core.schedule_once()
    assert core.obs.get("gate_mismatch_total").value() == 0
    assert core.obs.get("gate_path_total").value(path="device") >= 3
    assert core.obs.get("gate_passes_total").value() >= 1
    assert core.obs.get("unschedulable_total").value(reason="quota_held") > 0


def test_e2e_device_verify_pipelined():
    """Pipelined ticks through the device gate: exclude_keys +
    seed_admissions overlays decided on device, oracle-pinned."""
    from yunikorn_tpu.common.objects import make_pod

    cache, core = _e2e_core(E2E_YAML, gate_device=True)
    for w in range(3):
        _submit(core, f"appw{w}", "root.qa", "ua",
                [make_pod(f"dw{w}-{i}", cpu_milli=700, memory="128Mi")
                 for i in range(5)])
        core._pipeline_tick()
    for _ in range(4):
        core._pipeline_tick()
    assert core._pipeline_inflight is None
    assert core.obs.get("gate_mismatch_total").value() == 0
    assert core.obs.get("gate_path_total").value(path="device") >= 3


def test_e2e_gang_trace_device_verify():
    """Gang apps (placeholders + real asks) through device verify cycles."""
    from yunikorn_tpu.common.objects import make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import (
        AddApplicationRequest, AllocationRequest, ApplicationRequest,
        TaskGroup, UserGroupInfo)

    cache, core = _e2e_core(E2E_YAML, gate_device=True)
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="gang", queue_name="root.qa",
        user=UserGroupInfo(user="ua"),
        task_groups=[TaskGroup(name="tg", min_member=3,
                               min_resource={"cpu": "500m"})])]))
    phs = [make_pod(f"dph-{i}", cpu_milli=500) for i in range(3)]
    core.update_allocation(AllocationRequest(asks=[
        AllocationAsk(p.uid, "gang", get_pod_resource(p), placeholder=True,
                      task_group_name="tg", pod=p) for p in phs]))
    core.schedule_once()
    real = [make_pod(f"drm-{i}", cpu_milli=500) for i in range(3)]
    core.update_allocation(AllocationRequest(asks=[
        AllocationAsk(p.uid, "gang", get_pod_resource(p),
                      task_group_name="tg", pod=p) for p in real]))
    core.schedule_once()
    assert core.obs.get("gate_mismatch_total").value() == 0


def test_e2e_gate_fallback_still_legacy():
    """Oversized quantities raise GateFallback at extraction: no tier runs,
    the legacy loop decides, and the fallback path is counted — with the
    device pipeline on."""
    from yunikorn_tpu.common.objects import make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationRequest

    cache, core = _e2e_core(E2E_YAML, gate_verify=False, gate_device=True)
    p = make_pod("huge", cpu_milli=1 << 50)
    _submit(core, "appa", "root.qa", "ua", [p])
    core.schedule_once()
    assert core.obs.get("gate_path_total").value(path="fallback") >= 1
    assert core.obs.get("gate_path_total").value(path="device") == 0
