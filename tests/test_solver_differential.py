"""Differential fuzzing: the batched device solver vs the exact host
predicates. Random clusters + random constraint-bearing pods; every
assignment the solver makes must pass the host-side check, and every pod it
leaves unassigned must genuinely have no feasible node left. Catches encoder
and kernel bugs the curated suites miss (the reference leans on the
scheduler-framework's own predicate tests for this class).
"""
import random

import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import (Affinity, NodeSelectorRequirement,
                                         NodeSelectorTerm, Taint, Toleration,
                                         make_node, make_pod)
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.ops.host_predicates import pod_fits_node
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

ZONES = ["z0", "z1", "z2"]
DISKS = ["ssd", "hdd"]


def random_node(rng, i):
    labels = {"zone": rng.choice(ZONES), "disk": rng.choice(DISKS)}
    node = make_node(f"n{i}", cpu_milli=rng.choice([2000, 4000, 8000]),
                     memory=8 * 2**30, labels=labels)
    if rng.random() < 0.25:
        node.spec.taints = [Taint(key="dedicated", value="batch",
                                  effect="NoSchedule")]
    if rng.random() < 0.1:
        node.spec.unschedulable = True
    return node


def random_pod(rng, i):
    pod = make_pod(f"p{i}", cpu_milli=rng.choice([200, 500, 1000, 1800]),
                   memory=2**20)
    r = rng.random()
    if r < 0.25:
        pod.spec.node_selector = {"zone": rng.choice(ZONES)}
    elif r < 0.4:
        pod.spec.affinity = Affinity(node_required_terms=[NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement(
                "disk", rng.choice(["In", "NotIn"]), [rng.choice(DISKS)])])])
    if rng.random() < 0.2:
        pod.spec.tolerations = [Toleration(key="dedicated", operator="Equal",
                                           value="batch", effect="NoSchedule")]
    if rng.random() < 0.15:
        pod.spec.containers[0].ports = [
            {"hostPort": 9000 + rng.randint(0, 2), "protocol": "TCP"}]
    return pod


@pytest.mark.parametrize("seed", range(12))
def test_solver_matches_host_predicates(seed):
    rng = random.Random(seed)
    cache = SchedulerCache()
    nodes = [random_node(rng, i) for i in range(rng.randint(4, 12))]
    for n in nodes:
        cache.update_node(n)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [random_pod(rng, i) for i in range(rng.randint(8, 48))]
    asks = [AllocationAsk(p.uid, "diff-app", get_pod_resource(p), pod=p)
            for p in pods]
    batch = enc.build_batch(asks)
    result = solve_batch(batch, enc.nodes)
    assigned = np.asarray(result.assigned)[: batch.num_pods]

    by_name = {n.name: n for n in nodes}
    placed_on = {}                       # node name -> [pods]
    for i, pod in enumerate(pods):
        idx = int(assigned[i])
        if idx >= 0:
            placed_on.setdefault(enc.nodes.name_of(idx), []).append(pod)

    # 1. every placement satisfies the exact host predicates, with the other
    #    batch placements on the node counted as existing pods
    for name, placed in placed_on.items():
        node = by_name[name]
        free = get_node_free(cache, name)
        for k, pod in enumerate(placed):
            others = placed[:k] + placed[k + 1:]
            # resources: check the GROUP sum below; here check the
            # non-resource predicates + port conflicts inside the batch
            err = pod_fits_node(pod, node, free, others)
            assert err in (None, "insufficient resources"), (
                seed, name, pod.name, err)
        total = sum(get_pod_resource(p).get("cpu") for p in placed)
        assert total <= free.get("cpu"), (seed, name, total, free.get("cpu"))

    # 2. completeness: an unassigned pod must have NO node where it passes
    #    the host predicates with the remaining (post-batch) capacity
    for i, pod in enumerate(pods):
        if int(assigned[i]) >= 0:
            continue
        for name, node in by_name.items():
            free = get_node_free(cache, name)
            used = sum(get_pod_resource(p).get("cpu")
                       for p in placed_on.get(name, []))
            if pod_fits_node(pod, node, free, placed_on.get(name, [])) is None \
                    and get_pod_resource(pod).get("cpu") <= free.get("cpu") - used:
                raise AssertionError(
                    f"seed {seed}: solver left {pod.name} unassigned but "
                    f"node {name} fits it (free cpu "
                    f"{free.get('cpu') - used})")


def get_node_free(cache, name):
    info = cache.get_node(name)
    return info.available()


# --------------------------------------------------------------- locality fuzz
# Randomized topology spread / pod affinity / anti-affinity against a host
# re-simulation oracle: replay the solver's own accept order (accept_round,
# exported per pod) and check every count-dependent decision against exact
# K8s-semantics bookkeeping — the acceptance criterion is that each batch has
# a legal sequentialization consistent with the solver's round order,
# including across chained chunk boundaries (max_batch < N).

from yunikorn_tpu.common.objects import PodAffinityTerm, TopologySpreadConstraint
from yunikorn_tpu.snapshot.locality import (
    HOSTNAME_KEY,
    KIND_AFFINITY,
    KIND_ANTI_AFFINITY,
    KIND_SPREAD,
    _pod_anti_terms,
    _pod_constraints,
)

APPS = ["red", "blue", "green"]


def _dom_of(node, topo_key):
    v = node.metadata.labels.get(topo_key)
    if topo_key == HOSTNAME_KEY and v is None:
        v = node.name
    return v


class LocalityOracle:
    """Exact host bookkeeping of locality state as placements replay."""

    def __init__(self, nodes):
        self.nodes = {n.name: n for n in nodes}
        self.placed = []                     # [(pod, node_name)]

    def domains(self, topo_key):
        return {v for n in self.nodes.values()
                if (v := _dom_of(n, topo_key)) is not None}

    def counts(self, spec):
        c = {}
        for p, node_name in self.placed:
            v = _dom_of(self.nodes[node_name], spec.topo_key)
            if v is not None and spec.counts_pod(p):
                c[v] = c.get(v, 0) + 1
        return c

    def check(self, pod, node_name):
        """None if placing pod on node is legal under current state, else a
        reason string."""
        node = self.nodes[node_name]
        for kind, spec, skew in _pod_constraints(pod):
            v = _dom_of(node, spec.topo_key)
            c = self.counts(spec)
            doms = self.domains(spec.topo_key)
            minc = min((c.get(d, 0) for d in doms), default=0)
            total = sum(c.values())
            if kind == KIND_SPREAD:
                self_add = 1 if spec.counts_pod(pod) else 0
                if v is None or c.get(v, 0) + self_add - minc > max(1, skew):
                    return (f"spread violated: dom {v} count {c.get(v, 0)}"
                            f"+{self_add} min {minc} skew {skew}")
            elif kind == KIND_AFFINITY:
                seed = spec.counts_pod(pod)
                if v is None or not (c.get(v, 0) > 0 or (seed and total == 0)):
                    return (f"affinity violated: dom {v} count {c.get(v, 0)} "
                            f"total {total} seed {seed}")
            elif kind == KIND_ANTI_AFFINITY:
                if v is not None and c.get(v, 0) > 0:
                    return f"anti-affinity violated: dom {v} count {c.get(v, 0)}"
        # symmetry: placed pods' anti terms that match this pod block their
        # holders' domains
        for q, q_node in self.placed:
            for t in _pod_anti_terms(q):
                if not t.counts_pod(pod):
                    continue
                if _dom_of(self.nodes[q_node], t.topo_key) == \
                        _dom_of(node, t.topo_key):
                    return (f"symmetric anti violated: {q.name} on {q_node} "
                            f"holds a term matching {pod.name}")
        return None

    def place(self, pod, node_name):
        self.placed.append((pod, node_name))


def _replay_phase(pod, node_name, oracle, all_final):
    """Replay priority inside one round (lower = earlier):

    0. affinity SEEDERS — pods whose required-affinity domain ends the batch
       with no OTHER matching pod: their only legal slot is before any
       contributor lands (total==0 seeding), so they must go first.
    1. spread / anti pods — their checks are against counts at their own
       placement time; the solver's joint accept (level fill) admits orders
       that place them before the round's unconstrained contributors.
    2. unconstrained contributors — always legal, but they shift counts.
    3. affinity JOINERS — their domain does gain a matching pod, so placing
       them after everything satisfies cnt>0 regardless of who provided it.
    """
    cons = _pod_constraints(pod)
    kinds = [k for k, _, _ in cons]
    if KIND_AFFINITY in kinds:
        node = oracle.nodes[node_name]
        for kind, spec, _ in cons:
            if kind != KIND_AFFINITY:
                continue
            v = _dom_of(node, spec.topo_key)
            others = sum(
                1 for q, qn in all_final
                if q is not pod and spec.counts_pod(q)
                and _dom_of(oracle.nodes[qn], spec.topo_key) == v)
            if others == 0:
                return 0
        return 3
    if KIND_SPREAD in kinds or KIND_ANTI_AFFINITY in kinds:
        return 1
    return 2


def _tightness(pod, node_name, oracle):
    """How close this (currently legal) placement is to its own constraint
    boundaries — lower places first. Spread: remaining headroom under the
    skew. Anti: 0 (must precede any matcher). Others: +inf."""
    node = oracle.nodes[node_name]
    tight = 10**9
    for kind, spec, skew in _pod_constraints(pod):
        v = _dom_of(node, spec.topo_key)
        if v is None:
            continue
        if kind == KIND_SPREAD:
            c = oracle.counts(spec)
            doms = oracle.domains(spec.topo_key)
            minc = min((c.get(d, 0) for d in doms), default=0)
            self_add = 1 if spec.counts_pod(pod) else 0
            tight = min(tight,
                        max(1, skew) - (c.get(v, 0) + self_add - minc))
        elif kind == KIND_ANTI_AFFINITY:
            tight = min(tight, 0)
    return tight


def _random_order_exists(seed, rnd, oracle, round_pods, trace,
                         restarts=40):
    """Last-resort existence search: seeded random restarts of a plain
    first-legal greedy. Returns True (with oracle/trace advanced) when some
    order places the whole round."""
    base_len = len(oracle.placed)
    base_trace = len(trace)
    rng = random.Random((seed << 8) ^ rnd)
    for _ in range(restarts):
        pending = list(round_pods)
        rng.shuffle(pending)
        ok = True
        while pending:
            placed_one = False
            for i, (pod, node_name) in enumerate(pending):
                if oracle.check(pod, node_name) is None:
                    oracle.place(pod, node_name)
                    trace.append((pod.name, node_name))
                    pending.pop(i)
                    placed_one = True
                    break
            if not placed_one:
                ok = False
                break
        if ok:
            return True
        del oracle.placed[base_len:]
        del trace[base_trace:]
    return False


def replay_with_oracle(seed, oracle, placements):
    """placements: [(pod, node_name, accept_round)] — verify a legal
    sequentialization exists that is consistent with the solver's round
    order. Within a round, pods are placed greedily (most-constrained-first
    among the currently-legal); when the greedy sticks on a pod, that pod is
    PROMOTED to highest priority and the round replays — a legal order may
    require a tight pod to precede same-label contributors that consume its
    headroom, which no static priority can see. A round fails only when the
    stuck pod is already promoted (no order places it first either)."""
    all_final = list(oracle.placed) + [(p, n) for p, n, _ in placements]
    by_round = {}
    for pod, node_name, rnd in placements:
        by_round.setdefault(rnd, []).append((pod, node_name))
    trace = []

    def run_greedy(pending, promoted_rank):
        """Place all of pending if possible. Returns None on success, or
        ((pod, node_name), reason) for the pod it stuck on. Mutates
        oracle/trace. Promoted pods sort strictly before everything else,
        ordered by promotion recency (most recent first) so the newest
        promotion really is placed first when legal."""
        pending = list(pending)
        while pending:
            best = None
            last = None
            for i, (pod, node_name) in enumerate(pending):
                reason = oracle.check(pod, node_name)
                if reason is not None:
                    last = ((pod, node_name), reason)
                    continue
                pr = promoted_rank.get(id(pod))
                if pr is not None:
                    key = (-1, pr, i)
                else:
                    key = (0, _replay_phase(pod, node_name, oracle, all_final),
                           _tightness(pod, node_name, oracle), i)
                if best is None or key < best[0]:
                    best = (key, i, pod, node_name)
            if best is None:
                return last
            _, i, pod, node_name = best
            oracle.place(pod, node_name)
            trace.append((pod.name, node_name))
            pending.pop(i)
        return None

    for rnd in sorted(by_round):
        round_pods = sorted(
            by_round[rnd],
            key=lambda pn: _replay_phase(pn[0], pn[1], oracle, all_final))
        base_len = len(oracle.placed)
        base_trace = len(trace)
        promoted: list = []
        attempts = 0
        max_attempts = 2 * len(round_pods) + 8
        while True:
            attempts += 1
            promoted_rank = {id(p): r for r, (p, _) in enumerate(promoted)}
            stuck = run_greedy(promoted + [pn for pn in round_pods
                                          if id(pn[0]) not in promoted_rank],
                               promoted_rank)
            if stuck is None:
                break
            (pod, node_name), reason = stuck
            if promoted_rank.get(id(pod)) == 0 or attempts > max_attempts:
                # the promoted-greedy search is exhausted; before declaring
                # the joint accept illegal, try bounded random restarts — a
                # legal order may need a specific interleaving of the OTHER
                # pods (e.g. a min-domain contributor placed before the
                # stuck pod) that no greedy priority finds
                del oracle.placed[base_len:]
                del trace[base_trace:]
                if _random_order_exists(seed, rnd, oracle, by_round[rnd],
                                        trace):
                    break
                raise AssertionError(
                    f"seed {seed}: round {rnd} has no legal order found; "
                    f"stuck on ({pod.name}, {node_name}, {reason}) "
                    f"(promoted-greedy + random restarts); replay trace: "
                    f"{trace[base_trace:]}")
            # (re-)promote to the FRONT: a newer promotion may have displaced
            # this pod from first place and consumed its headroom
            promoted = ([(pod, node_name)]
                        + [e for e in promoted if id(e[0]) != id(pod)])
            del oracle.placed[base_len:]
            del trace[base_trace:]


def random_loc_pod(rng, i):
    app = rng.choice(APPS)
    pod = make_pod(f"lp{i}", cpu_milli=rng.choice([100, 200, 400]),
                   memory=2**20)
    pod.metadata.labels["app"] = app
    r = rng.random()
    sel = {"matchLabels": {"app": rng.choice(APPS)}}
    own_sel = {"matchLabels": {"app": app}}
    if r < 0.25:
        # hard topology spread (usually self-matching — the K8s idiom;
        # hostname topology sometimes — per-node balance, many domains)
        pod.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=rng.choice([1, 2]),
            topology_key="zone" if rng.random() < 0.8 else HOSTNAME_KEY,
            when_unsatisfiable="DoNotSchedule",
            label_selector=own_sel if rng.random() < 0.8 else sel)]
        if rng.random() < 0.2:
            # multi-constraint pod: spread + anti-affinity HOLDER — the
            # combination where cap-removal ordering vs the spread level
            # fill matters (pair exclusion must run before the fill)
            pod.spec.affinity = Affinity(pod_anti_affinity_required=[
                PodAffinityTerm(
                    label_selector=sel,
                    topology_key=rng.choice([HOSTNAME_KEY, "zone"]))])
    elif r < 0.3:
        # ScheduleAnyway spread: scoring-only — must never block placement
        # (the oracle checks hard rules; a soft constraint showing up as a
        # hard block is exactly the class of encoding bug to catch)
        pod.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=1, topology_key="zone",
            when_unsatisfiable="ScheduleAnyway", label_selector=own_sel)]
    elif r < 0.45:
        # required anti-affinity; selector may or may not match the pod
        pod.spec.affinity = Affinity(pod_anti_affinity_required=[
            PodAffinityTerm(
                label_selector=sel if rng.random() < 0.5 else own_sel,
                topology_key=rng.choice([HOSTNAME_KEY, "zone"]))])
    elif r < 0.6:
        # required affinity on zone; self-matching pods may seed
        pod.spec.affinity = Affinity(pod_affinity_required=[
            PodAffinityTerm(
                label_selector=own_sel if rng.random() < 0.5 else sel,
                topology_key="zone")])
    # else: plain pod — but its app label may make it a contributor to
    # someone else's selector (the hard case for in-batch counting)
    return pod


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("max_batch", [65536, 16])
def test_locality_solver_matches_replay_oracle(seed, max_batch):
    """Every locality-bearing batch the solver commits must replay cleanly
    through the host oracle in the solver's own accept order — max_batch=16
    (solve_batch floors the chunk bucket at 64, and min_batch=128 makes
    N=128 > 64) forces the chained solve_chunked path so cross-chunk count
    carry is fuzzed too (VERDICT r4 item 5)."""
    rng = random.Random(1000 + seed)
    cache = SchedulerCache()
    nodes = []
    for i in range(rng.randint(6, 12)):
        labels = {"zone": f"z{i % 3}"}
        n = make_node(f"n{i}", cpu_milli=rng.choice([4000, 8000]),
                      memory=8 * 2**30, labels=labels)
        nodes.append(n)
        cache.update_node(n)
    # existing assigned pods: locality counts must seed from cluster state
    existing = []
    for i in range(rng.randint(0, 5)):
        p = make_pod(f"ex{i}", cpu_milli=100, memory=2**20,
                     node_name=rng.choice(nodes).name, phase="Running",
                     labels={"app": rng.choice(APPS)})
        if rng.random() < 0.3:
            p.spec.affinity = Affinity(pod_anti_affinity_required=[
                PodAffinityTerm(
                    label_selector={"matchLabels": {"app": rng.choice(APPS)}},
                    topology_key=rng.choice([HOSTNAME_KEY, "zone"]))])
        cache.update_pod(p)
        existing.append(p)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [random_loc_pod(rng, i) for i in range(rng.randint(10, 40))]
    # pending pods enter the cache before asks flow (context does this) so
    # anti-affinity symmetry sees in-batch holders
    for p in pods:
        cache.update_pod(p)
    asks = [AllocationAsk(p.uid, "loc-app", get_pod_resource(p), pod=p)
            for p in pods]
    if max_batch == 16:
        batch = enc.build_batch(asks, min_batch=128)
    else:
        batch = enc.build_batch(asks)
    result = solve_batch(batch, enc.nodes, max_batch=max_batch)
    assigned = np.asarray(result.assigned)[: batch.num_pods]
    around = np.asarray(result.accept_round)[: batch.num_pods]

    # Groups whose constraints overflow the tensor encoding take the exact
    # host-mask fallback and are serialized one-pod-per-solve (their own
    # contract, tested in test_locality.py); the rest of the group's rows are
    # parked (valid=False) for the core's drain loop, which solve_batch alone
    # does not run. Exclude those pods from the replay/completeness here —
    # the oracle fuzzes the TENSOR path's count decisions.
    fb_gids = (set(batch.locality.fallback)
               if batch.locality is not None and batch.locality.fallback
               else set())
    skip = [int(batch.group_id[i]) in fb_gids or not bool(batch.valid[i])
            for i in range(len(pods))]

    oracle = LocalityOracle(nodes)
    for p in existing:
        oracle.place(p, p.spec.node_name)
    placements = []
    fb_placements = []
    for i, pod in enumerate(pods):
        idx = int(assigned[i])
        if idx < 0:
            continue
        if skip[i]:
            fb_placements.append((pod, enc.nodes.name_of(idx)))
            continue
        placements.append((pod, enc.nodes.name_of(idx), int(around[i])))
    # shown by pytest only on failure: the full placement set for triage
    print(f"placements: {[(p.name, n, r) for p, n, r in placements]}")
    replay_with_oracle(seed, oracle, placements)
    # host-serialized placements enter the oracle state unchecked AFTER the
    # replay (their round order vs the tensor path is not modeled) so the
    # completeness check below still sees the true final state
    for pod, node_name in fb_placements:
        oracle.place(pod, node_name)

    # completeness under the final state: an unassigned pod must have no node
    # that fits it (resources + predicates + locality legal w.r.t. the final
    # placed set) — catches cap-induced starvation of feasible pods
    used = {}
    for pod, node_name in [(p, n) for p, n, _ in placements] + fb_placements:
        used[node_name] = used.get(node_name, 0) + \
            get_pod_resource(pod).get("cpu")
    for i, pod in enumerate(pods):
        if int(assigned[i]) >= 0 or skip[i]:
            continue
        for n in nodes:
            free_cpu = get_node_free(cache, n.name).get("cpu") - \
                used.get(n.name, 0)
            if get_pod_resource(pod).get("cpu") > free_cpu:
                continue
            if oracle.check(pod, n.name) is None:
                raise AssertionError(
                    f"seed {seed}: {pod.name} left unassigned but node "
                    f"{n.name} is legal and has {free_cpu}m cpu free")
