"""Differential fuzzing: the batched device solver vs the exact host
predicates. Random clusters + random constraint-bearing pods; every
assignment the solver makes must pass the host-side check, and every pod it
leaves unassigned must genuinely have no feasible node left. Catches encoder
and kernel bugs the curated suites miss (the reference leans on the
scheduler-framework's own predicate tests for this class).
"""
import random

import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import (Affinity, NodeSelectorRequirement,
                                         NodeSelectorTerm, Taint, Toleration,
                                         make_node, make_pod)
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.ops.host_predicates import pod_fits_node
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

ZONES = ["z0", "z1", "z2"]
DISKS = ["ssd", "hdd"]


def random_node(rng, i):
    labels = {"zone": rng.choice(ZONES), "disk": rng.choice(DISKS)}
    node = make_node(f"n{i}", cpu_milli=rng.choice([2000, 4000, 8000]),
                     memory=8 * 2**30, labels=labels)
    if rng.random() < 0.25:
        node.spec.taints = [Taint(key="dedicated", value="batch",
                                  effect="NoSchedule")]
    if rng.random() < 0.1:
        node.spec.unschedulable = True
    return node


def random_pod(rng, i):
    pod = make_pod(f"p{i}", cpu_milli=rng.choice([200, 500, 1000, 1800]),
                   memory=2**20)
    r = rng.random()
    if r < 0.25:
        pod.spec.node_selector = {"zone": rng.choice(ZONES)}
    elif r < 0.4:
        pod.spec.affinity = Affinity(node_required_terms=[NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement(
                "disk", rng.choice(["In", "NotIn"]), [rng.choice(DISKS)])])])
    if rng.random() < 0.2:
        pod.spec.tolerations = [Toleration(key="dedicated", operator="Equal",
                                           value="batch", effect="NoSchedule")]
    if rng.random() < 0.15:
        pod.spec.containers[0].ports = [
            {"hostPort": 9000 + rng.randint(0, 2), "protocol": "TCP"}]
    return pod


@pytest.mark.parametrize("seed", range(12))
def test_solver_matches_host_predicates(seed):
    rng = random.Random(seed)
    cache = SchedulerCache()
    nodes = [random_node(rng, i) for i in range(rng.randint(4, 12))]
    for n in nodes:
        cache.update_node(n)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [random_pod(rng, i) for i in range(rng.randint(8, 48))]
    asks = [AllocationAsk(p.uid, "diff-app", get_pod_resource(p), pod=p)
            for p in pods]
    batch = enc.build_batch(asks)
    result = solve_batch(batch, enc.nodes)
    assigned = np.asarray(result.assigned)[: batch.num_pods]

    by_name = {n.name: n for n in nodes}
    placed_on = {}                       # node name -> [pods]
    for i, pod in enumerate(pods):
        idx = int(assigned[i])
        if idx >= 0:
            placed_on.setdefault(enc.nodes.name_of(idx), []).append(pod)

    # 1. every placement satisfies the exact host predicates, with the other
    #    batch placements on the node counted as existing pods
    for name, placed in placed_on.items():
        node = by_name[name]
        free = get_node_free(cache, name)
        for k, pod in enumerate(placed):
            others = placed[:k] + placed[k + 1:]
            # resources: check the GROUP sum below; here check the
            # non-resource predicates + port conflicts inside the batch
            err = pod_fits_node(pod, node, free, others)
            assert err in (None, "insufficient resources"), (
                seed, name, pod.name, err)
        total = sum(get_pod_resource(p).get("cpu") for p in placed)
        assert total <= free.get("cpu"), (seed, name, total, free.get("cpu"))

    # 2. completeness: an unassigned pod must have NO node where it passes
    #    the host predicates with the remaining (post-batch) capacity
    for i, pod in enumerate(pods):
        if int(assigned[i]) >= 0:
            continue
        for name, node in by_name.items():
            free = get_node_free(cache, name)
            used = sum(get_pod_resource(p).get("cpu")
                       for p in placed_on.get(name, []))
            if pod_fits_node(pod, node, free, placed_on.get(name, [])) is None \
                    and get_pod_resource(pod).get("cpu") <= free.get("cpu") - used:
                raise AssertionError(
                    f"seed {seed}: solver left {pod.name} unassigned but "
                    f"node {name} fits it (free cpu "
                    f"{free.get('cpu') - used})")


def get_node_free(cache, name):
    info = cache.get_node(name)
    return info.available()
