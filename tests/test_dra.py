"""DynamicResourceAllocation: claim-aware feasibility (reference gates a DRA
manager into the Context, context.go:116-130, and plumbs ResourceClaim
informers, apifactory.go:39-59). Structured-parameters model: ResourceSlices
advertise per-node devices, claims pin to a node at assume time."""
import numpy as np

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import (ResourceClaim, ResourceSlice,
                                         make_node, make_pod)
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder


def make_env(nodes):
    cache = SchedulerCache()
    for n in nodes:
        cache.update_node(n)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return cache, enc


def ask_for(pod):
    return AllocationAsk(pod.uid, "app-1", get_pod_resource(pod), pod=pod)


def assignments(enc, res, batch):
    a = np.asarray(res.assigned)
    return {k: (enc.nodes.name_of(int(a[i])) if a[i] >= 0 else None)
            for i, k in enumerate(batch.ask_keys)}


def claim_pod(name, claims):
    p = make_pod(name, cpu_milli=100, memory=2**20)
    p.spec.resource_claims = list(claims)
    return p


def test_claim_pod_schedules_only_on_device_node():
    cache, enc = make_env([make_node(f"n{i}", cpu_milli=8000) for i in range(3)])
    cache.update_resource_slice(ResourceSlice("n2", "gpu.example.com", 1))
    cache.update_resource_claim(ResourceClaim("c1", "default", "gpu.example.com"))
    p = claim_pod("wants-gpu", ["c1"])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "n2"


def test_allocated_claim_pins_to_its_node():
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    cache.update_resource_slice(ResourceSlice("n0", "gpu.example.com", 4))
    cache.update_resource_slice(ResourceSlice("n1", "gpu.example.com", 4))
    cache.update_resource_claim(ResourceClaim(
        "c1", "default", "gpu.example.com", allocated_node="n1"))
    p = claim_pod("pinned", ["c1"])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "n1"


def test_unknown_claim_stays_pending():
    cache, enc = make_env([make_node("n0")])
    p = claim_pod("orphan", ["nope"])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] is None


def test_exhausted_devices_hold_pod_pending():
    cache, enc = make_env([make_node("n0")])
    cache.update_resource_slice(ResourceSlice("n0", "gpu.example.com", 1))
    cache.update_resource_claim(ResourceClaim(
        "c-used", "default", "gpu.example.com", allocated_node="n0",
        reserved_for=["other-pod"]))
    cache.update_resource_claim(ResourceClaim("c-new", "default", "gpu.example.com"))
    p = claim_pod("wants-gpu", ["c-new"])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] is None  # only device taken


def test_unallocated_claim_group_serialized_then_follows():
    """Two pods sharing one unallocated claim: first solve places one and the
    assume pins the claim; the second follows onto the SAME node next cycle
    (claims are node-local)."""
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    cache.update_resource_slice(ResourceSlice("n0", "gpu.example.com", 2))
    cache.update_resource_slice(ResourceSlice("n1", "gpu.example.com", 2))
    cache.update_resource_claim(ResourceClaim("shared", "default", "gpu.example.com"))
    pods = [claim_pod(f"s{i}", ["shared"]) for i in range(2)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    placed = {k: v for k, v in got.items() if v is not None}
    assert len(placed) == 1
    first_key, node = next(iter(placed.items()))
    first = next(p for p in pods if p.uid == first_key)
    first.spec.node_name = node
    cache.assume_pod(first, True)  # pins the claim
    assert cache.resource_claims["default/shared"].allocated_node == node
    second = next(p for p in pods if p.uid != first_key)
    batch2 = enc.build_batch([ask_for(second)])
    res2 = solve_batch(batch2, enc.nodes)
    assert assignments(enc, res2, batch2)[second.uid] == node


def test_claim_released_on_pod_removal():
    cache, enc = make_env([make_node("n0")])
    cache.update_resource_slice(ResourceSlice("n0", "gpu.example.com", 1))
    cache.update_resource_claim(ResourceClaim("c1", "default", "gpu.example.com"))
    p = claim_pod("holder", ["c1"])
    cache.update_pod(p)
    p2 = p.deepcopy()
    p2.spec.node_name = "n0"
    cache.assume_pod(p2, True)
    assert cache.resource_claims["default/c1"].allocated_node == "n0"
    cache.remove_pod(p2)
    assert cache.resource_claims["default/c1"].allocated_node == ""


def test_dra_e2e_through_shim():
    """Full path: conf gate on, claim/slice informers feed the cache, a
    claim-bearing pod binds on the device node."""
    from yunikorn_tpu.shim import mock_scheduler
    from yunikorn_tpu.cache import task as task_mod

    ms = mock_scheduler.MockScheduler()
    ms.init(conf_extra={"service.enableDRA": "true"})
    ms.start()
    try:
        ms.add_nodes([make_node(f"n{i}", cpu_milli=4000) for i in range(3)])
        ms.cluster.add_resource_slice(ResourceSlice("n1", "tpu.example.com", 1))
        ms.cluster.add_resource_claim(ResourceClaim("tc", "default", "tpu.example.com"))
        pod = make_pod("dra-pod", cpu_milli=500, memory=2**27,
                       labels={constants.LABEL_APPLICATION_ID: "dra-app"},
                       scheduler_name=constants.SCHEDULER_NAME)
        pod.spec.resource_claims = ["tc"]
        ms.add_pod(pod)
        ms.wait_for_task_state("dra-app", pod.uid, task_mod.BOUND)
        assert ms.get_pod_assignment(pod) == "n1"
    finally:
        ms.stop()


def test_same_class_demand_not_overallocated_within_solve():
    """Two groups (distinct claims) of one device class racing one device:
    only one may place per solve; the second follows only if devices remain."""
    cache, enc = make_env([make_node("n0"), make_node("n1")])
    cache.update_resource_slice(ResourceSlice("n0", "gpu.example.com", 1))
    cache.update_resource_claim(ResourceClaim("cA", "default", "gpu.example.com"))
    cache.update_resource_claim(ResourceClaim("cB", "default", "gpu.example.com"))
    pa, pb = claim_pod("pa", ["cA"]), claim_pod("pb", ["cB"])
    batch = enc.build_batch([ask_for(pa), ask_for(pb)])
    res = solve_batch(batch, enc.nodes)
    got = assignments(enc, res, batch)
    placed = {k: v for k, v in got.items() if v is not None}
    assert len(placed) == 1 and list(placed.values()) == ["n0"]
    # assume the winner: the device is gone; the loser stays pending forever
    win_key, node = next(iter(placed.items()))
    winner = pa if pa.uid == win_key else pb
    loser = pb if winner is pa else pa
    w = winner.deepcopy(); w.spec.node_name = node
    cache.update_pod(winner); cache.assume_pod(w, True)
    batch2 = enc.build_batch([ask_for(loser)])
    res2 = solve_batch(batch2, enc.nodes)
    assert assignments(enc, res2, batch2)[loser.uid] is None


def test_multi_claim_pod_needs_enough_devices():
    """One pod with two same-class claims needs TWO free devices on a node."""
    cache, enc = make_env([make_node("small"), make_node("big")])
    cache.update_resource_slice(ResourceSlice("small", "gpu.example.com", 1))
    cache.update_resource_slice(ResourceSlice("big", "gpu.example.com", 2))
    cache.update_resource_claim(ResourceClaim("c1", "default", "gpu.example.com"))
    cache.update_resource_claim(ResourceClaim("c2", "default", "gpu.example.com"))
    p = claim_pod("dual", ["c1", "c2"])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert assignments(enc, res, batch)[p.uid] == "big"


def test_informer_echo_does_not_free_reserved_device():
    cache, enc = make_env([make_node("n0")])
    cache.update_resource_slice(ResourceSlice("n0", "gpu.example.com", 1))
    cache.update_resource_claim(ResourceClaim("c1", "default", "gpu.example.com"))
    p = claim_pod("holder", ["c1"])
    cache.update_pod(p)
    p2 = p.deepcopy(); p2.spec.node_name = "n0"
    cache.assume_pod(p2, True)
    # API-server echo without allocation state must keep the reservation
    cache.update_resource_claim(ResourceClaim("c1", "default", "gpu.example.com"))
    claim = cache.resource_claims["default/c1"]
    assert claim.allocated_node == "n0" and p.uid in claim.reserved_for
