"""Exhaustive FSM transition matrices for Application and Task.

Reference bar: application_state_test.go / task_state_test.go assert every
(state, event) pair. Here the full matrix is written out explicitly: any
change to the transition tables — intended or accidental (mutation) — fails
exactly the affected cells. Driven on the bare FSM (no side-effect
callbacks), which shares the Transition tables with the live objects.
"""
import pytest

from yunikorn_tpu.cache import application as app_mod
from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.utils.fsm import FSM, InvalidEventError


def allowed_map(transitions):
    out = {}
    for t in transitions:
        for src in t.sources:
            out[(src, t.event)] = t.destination
    return out


APP_STATES = [app_mod.NEW, app_mod.SUBMITTED, app_mod.ACCEPTED, app_mod.RESERVING,
              app_mod.RUNNING, app_mod.REJECTED, app_mod.COMPLETED, app_mod.KILLING,
              app_mod.KILLED, app_mod.FAILING, app_mod.FAILED, app_mod.RESUMING]
APP_EVENTS = [app_mod.SUBMIT_APPLICATION, app_mod.ACCEPT_APPLICATION,
              app_mod.TRY_RESERVE, app_mod.UPDATE_RESERVATION,
              app_mod.RESUMING_APPLICATION, app_mod.APP_TASK_COMPLETED,
              app_mod.RUN_APPLICATION, app_mod.RELEASE_APP_ALLOCATION,
              app_mod.COMPLETE_APPLICATION, app_mod.REJECT_APPLICATION,
              app_mod.FAIL_APPLICATION, app_mod.KILL_APPLICATION,
              app_mod.KILLED_APPLICATION]

# the full expected matrix, written out (reference application_state.go:364-470)
APP_EXPECTED = {
    (app_mod.NEW, app_mod.SUBMIT_APPLICATION): app_mod.SUBMITTED,
    (app_mod.SUBMITTED, app_mod.ACCEPT_APPLICATION): app_mod.ACCEPTED,
    (app_mod.SUBMITTED, app_mod.REJECT_APPLICATION): app_mod.REJECTED,
    (app_mod.SUBMITTED, app_mod.FAIL_APPLICATION): app_mod.FAILING,
    (app_mod.ACCEPTED, app_mod.TRY_RESERVE): app_mod.RESERVING,
    (app_mod.ACCEPTED, app_mod.RUN_APPLICATION): app_mod.RUNNING,
    (app_mod.ACCEPTED, app_mod.RELEASE_APP_ALLOCATION): app_mod.RUNNING,
    (app_mod.ACCEPTED, app_mod.FAIL_APPLICATION): app_mod.FAILING,
    (app_mod.ACCEPTED, app_mod.KILL_APPLICATION): app_mod.KILLING,
    (app_mod.RESERVING, app_mod.UPDATE_RESERVATION): app_mod.RESERVING,
    (app_mod.RESERVING, app_mod.RESUMING_APPLICATION): app_mod.RESUMING,
    (app_mod.RESERVING, app_mod.RUN_APPLICATION): app_mod.RUNNING,
    (app_mod.RESERVING, app_mod.RELEASE_APP_ALLOCATION): app_mod.RUNNING,
    (app_mod.RESERVING, app_mod.FAIL_APPLICATION): app_mod.FAILING,
    (app_mod.RESERVING, app_mod.KILL_APPLICATION): app_mod.KILLING,
    (app_mod.RESUMING, app_mod.APP_TASK_COMPLETED): app_mod.RESUMING,
    (app_mod.RESUMING, app_mod.RUN_APPLICATION): app_mod.RUNNING,
    (app_mod.RESUMING, app_mod.RELEASE_APP_ALLOCATION): app_mod.RESUMING,
    (app_mod.RUNNING, app_mod.RUN_APPLICATION): app_mod.RUNNING,
    (app_mod.RUNNING, app_mod.RELEASE_APP_ALLOCATION): app_mod.RUNNING,
    (app_mod.RUNNING, app_mod.COMPLETE_APPLICATION): app_mod.COMPLETED,
    (app_mod.RUNNING, app_mod.FAIL_APPLICATION): app_mod.FAILING,
    (app_mod.RUNNING, app_mod.KILL_APPLICATION): app_mod.KILLING,
    (app_mod.FAILING, app_mod.RELEASE_APP_ALLOCATION): app_mod.FAILING,
    (app_mod.FAILING, app_mod.FAIL_APPLICATION): app_mod.FAILED,
    (app_mod.REJECTED, app_mod.FAIL_APPLICATION): app_mod.FAILED,
    (app_mod.KILLING, app_mod.KILLED_APPLICATION): app_mod.KILLED,
}


@pytest.mark.parametrize("state", APP_STATES)
@pytest.mark.parametrize("event", APP_EVENTS)
def test_application_fsm_matrix(state, event):
    fsm = FSM(state, app_mod._TRANSITIONS, {})
    expected = APP_EXPECTED.get((state, event))
    if expected is None:
        with pytest.raises(InvalidEventError):
            fsm.event(event)
        assert fsm.current == state  # unchanged on rejection
    else:
        fsm.event(event)
        assert fsm.current == expected


def test_application_matrix_is_exhaustive():
    """The explicit matrix covers the live table exactly — a new or removed
    transition must be acknowledged here."""
    assert allowed_map(app_mod._TRANSITIONS) == APP_EXPECTED


TASK_STATES = list(task_mod.ANY)
TASK_EVENTS = [task_mod.INIT_TASK, task_mod.SUBMIT_TASK, task_mod.TASK_ALLOCATED,
               task_mod.TASK_BOUND, task_mod.COMPLETE_TASK, task_mod.KILL_TASK,
               task_mod.TASK_KILLED, task_mod.TASK_REJECTED, task_mod.TASK_FAIL,
               task_mod.TASK_RETRY]

TASK_EXPECTED = {}
for s in task_mod.ANY:
    TASK_EXPECTED[(s, task_mod.COMPLETE_TASK)] = task_mod.COMPLETED
TASK_EXPECTED.update({
    (task_mod.NEW, task_mod.INIT_TASK): task_mod.PENDING,
    (task_mod.NEW, task_mod.TASK_REJECTED): task_mod.REJECTED,
    (task_mod.NEW, task_mod.TASK_FAIL): task_mod.FAILED,
    (task_mod.PENDING, task_mod.SUBMIT_TASK): task_mod.SCHEDULING,
    (task_mod.PENDING, task_mod.KILL_TASK): task_mod.KILLING,
    (task_mod.PENDING, task_mod.TASK_REJECTED): task_mod.REJECTED,
    (task_mod.PENDING, task_mod.TASK_FAIL): task_mod.FAILED,
    (task_mod.SCHEDULING, task_mod.TASK_ALLOCATED): task_mod.ALLOCATED,
    (task_mod.SCHEDULING, task_mod.KILL_TASK): task_mod.KILLING,
    (task_mod.SCHEDULING, task_mod.TASK_REJECTED): task_mod.REJECTED,
    (task_mod.SCHEDULING, task_mod.TASK_FAIL): task_mod.FAILED,
    (task_mod.ALLOCATED, task_mod.TASK_BOUND): task_mod.BOUND,
    (task_mod.ALLOCATED, task_mod.KILL_TASK): task_mod.KILLING,
    (task_mod.ALLOCATED, task_mod.TASK_FAIL): task_mod.FAILED,
    # bind raced cluster state (node deleted mid-bind): allocation released,
    # task re-queues and re-submits a fresh ask (bounded by BIND_RETRY_MAX)
    (task_mod.ALLOCATED, task_mod.TASK_RETRY): task_mod.PENDING,
    (task_mod.BOUND, task_mod.KILL_TASK): task_mod.KILLING,
    (task_mod.KILLING, task_mod.TASK_KILLED): task_mod.KILLED,
    (task_mod.REJECTED, task_mod.TASK_FAIL): task_mod.FAILED,
    (task_mod.COMPLETED, task_mod.TASK_ALLOCATED): task_mod.COMPLETED,
})


@pytest.mark.parametrize("state", TASK_STATES)
@pytest.mark.parametrize("event", TASK_EVENTS)
def test_task_fsm_matrix(state, event):
    fsm = FSM(state, task_mod._TRANSITIONS, {})
    expected = TASK_EXPECTED.get((state, event))
    if expected is None:
        with pytest.raises(InvalidEventError):
            fsm.event(event)
        assert fsm.current == state
    else:
        fsm.event(event)
        assert fsm.current == expected


def test_task_matrix_is_exhaustive():
    assert allowed_map(task_mod._TRANSITIONS) == TASK_EXPECTED
