"""Learned dispatch policy (round 17, solver.policy=learned).

Pins the subsystem's four safety contracts plus the training loop:
  - feature extraction is deterministic and fixed-shape across resource-
    vocab widths (every compiled learned variant is a standard bucket);
  - an UNTRAINED checkpoint is inert: the learned solve is bit-identical
    to greedy and the duel commits the greedy plan;
  - a corrupt / schema-mismatched checkpoint REJECTS at load with the
    previous policy retained, and a checkpoint swap changes the AOT
    fingerprint (a stale stored executable can never serve);
  - the N-way choose_plan fold is priority-guarded pairwise (the three-
    plan starvation regression) and ties keep the incumbent;
  - a wedged/failed learned dispatch degrades to greedy placements
    without wedging the loop (the supervised-ladder chaos case);
  - the trainer learns the fragmented-alignment win end to end (record
    duels -> fit -> the learned arm packs more with no placement loss).
"""
import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.si import (
    AddApplicationRequest,
    AllocationAsk,
    AllocationRequest,
    ApplicationRequest,
    NodeAction,
    NodeInfo,
    NodeRequest,
    RegisterResourceManagerRequest,
    UserGroupInfo,
)
from yunikorn_tpu.core.scheduler import CoreScheduler, SolverOptions
from yunikorn_tpu.ops import pack_solve as pack_mod
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.policy import features as pf
from yunikorn_tpu.policy import net as pnet
from yunikorn_tpu.policy import train as ptrain


def _import_policy_bench():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import policy_bench

    return policy_bench


class _CB:
    def update_allocation(self, r): pass
    def update_application(self, r): pass
    def update_node(self, r): pass
    def predicates(self, a): return None
    def preemption_predicates(self, a): return None
    def send_event(self, e): pass
    def update_container_scheduling_state(self, r): pass
    def get_state_dump(self): return "{}"


def make_core(policy="learned", checkpoint=""):
    cache = SchedulerCache()
    core = CoreScheduler(cache, solver_options=SolverOptions(
        policy=policy, policy_checkpoint=checkpoint))
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="t", policy_group="queues",
                                       config=""), _CB())
    return cache, core


def run_core_trace(core, cache, n_nodes=32, waves=2, per_wave=60, cpu=400):
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
    from yunikorn_tpu.common.resource import get_pod_resource

    nodes = make_kwok_nodes(n_nodes)
    infos = []
    for n in nodes:
        cache.update_node(n)
        infos.append(NodeInfo(node_id=n.name, action=NodeAction.CREATE))
    core.update_node(NodeRequest(nodes=infos))
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="app", queue_name="root.q",
        user=UserGroupInfo(user="u"))]))
    placements = {}
    names = {}
    for w in range(waves):
        pods = make_sleep_pods(per_wave, "app", queue="root.q",
                               name_prefix=f"w{w}", cpu_milli=cpu)
        asks = []
        for p in pods:
            names[p.uid] = p.metadata.name
            asks.append(AllocationAsk(p.uid, "app", get_pod_resource(p),
                                      pod=p))
        core.update_allocation(AllocationRequest(asks=asks))
        core.schedule_once()
        app = core.partition.applications.get("app")
        for key, alloc in app.allocations.items():
            placements[names.get(key, key)] = alloc.node_id
    return placements


# ---------------------------------------------------------------------------
# features
# ---------------------------------------------------------------------------
def test_feature_extractor_determinism_and_fixed_shapes():
    rng = np.random.RandomState(0)
    for r in (1, 2, 4, 6):        # vocab widths narrower AND wider than 4
        req = rng.randint(0, 1000, size=(16, r)).astype(np.int32)
        cap = rng.randint(1000, 9000, size=(8, r)).astype(np.int32)
        free = np.maximum(cap - rng.randint(0, 900, size=(8, r)), 0)
        inv = pf.inv_capacity_scale(cap)
        a = np.asarray(pf.pod_features(req, inv))
        b = np.asarray(pf.pod_features(req, inv))
        na = np.asarray(pf.node_features(free, cap, inv))
        nb = np.asarray(pf.node_features(free, cap, inv))
        # deterministic + bucket-shape stable: F is FIXED regardless of R
        assert np.array_equal(a, b) and np.array_equal(na, nb)
        assert a.shape == (16, pf.F_POD)
        assert na.shape == (8, pf.F_NODE)
        assert np.isfinite(a).all() and np.isfinite(na).all()


def test_features_distinguish_empty_heterogeneous_flavors():
    """The round-17 training-signal pin: two EMPTY nodes of opposite
    resource shape must embed differently (fractions alone cannot tell a
    cpu-rich node from a mem-rich one — see node_features)."""
    cap = np.array([[8000, 4096], [2000, 16384]], np.int32)
    inv = pf.inv_capacity_scale(cap)
    f = np.asarray(pf.node_features(cap.copy(), cap, inv))
    assert not np.allclose(f[0], f[1])


# ---------------------------------------------------------------------------
# untrained-is-inert + duel floor
# ---------------------------------------------------------------------------
def test_untrained_net_solve_bit_identical_and_duel_keeps_greedy():
    pb = _import_policy_bench()
    enc, batch, priorities = pb.build(64, 32, seed=0)
    n = batch.num_pods
    g = solve_batch(batch, enc.nodes)
    ga = np.asarray(g.assigned)[:n]
    gf = np.asarray(g.free_after)
    l = solve_batch(batch, enc.nodes, learned=(pnet.init_params(5), 11))
    la = np.asarray(l.assigned)[:n]
    lf = np.asarray(l.free_after)
    assert np.array_equal(ga, la)
    assert np.array_equal(gf, lf)
    winner, _ = pack_mod.choose_plan_n(
        [("greedy", ga), ("learned", la)], batch.req.astype(np.int32),
        batch.valid, priorities=priorities)
    assert winner == "greedy"     # tie keeps the incumbent — commit == greedy


def test_core_untrained_checkpoint_commits_bit_identical_to_greedy(tmp_path):
    prefix = str(tmp_path / "ck")
    pnet.save_checkpoint(prefix, pnet.init_params(0), epoch=1)
    cache_l, core_l = make_core("learned", checkpoint=prefix)
    placements_l = run_core_trace(core_l, cache_l)
    cache_g, core_g = make_core("greedy")
    placements_g = run_core_trace(core_g, cache_g)
    assert placements_l == placements_g
    duels = core_l.obs.get("policy_duels_total")
    assert duels.value(policy="learned", outcome="lost") == 2
    assert duels.value(policy="greedy", outcome="won") == 2
    assert core_l.obs.get("policy_plans_total").value(
        outcome="fell_back") == 2
    entry = core_l.metrics["last_cycle"]["default"]
    assert entry["solver_policy"] == "greedy"
    assert entry["learned_util"] == 1.0
    assert entry["checkpoint"] == core_l._policy_ckpt.hash


def test_core_without_checkpoint_skips_learned_arm():
    cache, core = make_core("learned")
    placements = run_core_trace(core, cache, waves=1)
    assert len(placements) == 60
    assert core.obs.get("policy_plans_total").value(outcome="skipped") >= 1
    assert core.metrics["last_cycle"]["default"]["policy_skip"] \
        == "no-checkpoint"


# ---------------------------------------------------------------------------
# checkpoint lifecycle
# ---------------------------------------------------------------------------
def test_checkpoint_roundtrip_and_stable_hash(tmp_path):
    params = pnet.init_params(3)
    prefix = str(tmp_path / "ck")
    saved = pnet.save_checkpoint(prefix, params, epoch=7,
                                 meta={"note": "t"})
    loaded = pnet.load_checkpoint(prefix)
    assert loaded.hash == saved.hash == pnet.params_hash(params)
    assert loaded.epoch == 7
    for (a, b) in zip(pnet._flatten(params).values(),
                      pnet._flatten(loaded.params).values()):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_corrupt_checkpoint_rejected_with_previous_policy_retained(tmp_path):
    good = str(tmp_path / "good")
    pnet.save_checkpoint(good, pnet.init_params(0), epoch=1)
    bad = str(tmp_path / "bad")
    pnet.save_checkpoint(bad, pnet.init_params(1), epoch=2)
    with open(bad + ".npz", "r+b") as f:      # flip payload bytes
        f.seek(100)
        f.write(b"\xde\xad\xbe\xef")
    with pytest.raises(pnet.CheckpointError):
        pnet.load_checkpoint(bad)
    cache, core = make_core("learned", checkpoint=good)
    active = core._policy_ckpt.hash
    assert core.set_policy_checkpoint(bad) is False
    assert core._policy_ckpt.hash == active   # previous policy retained
    assert core.obs.get("policy_checkpoint_rejected_total").value() == 1


def test_feature_schema_mismatch_rejected(tmp_path):
    prefix = str(tmp_path / "ck")
    pnet.save_checkpoint(prefix, pnet.init_params(0), epoch=1)
    with open(prefix + ".json") as f:
        manifest = json.load(f)
    manifest["feature_version"] = pf.FEATURE_VERSION + 1
    with open(prefix + ".json", "w") as f:
        json.dump(manifest, f)
    with pytest.raises(pnet.CheckpointError, match="feature schema"):
        pnet.load_checkpoint(prefix)


def test_shape_drift_rejected(tmp_path):
    prefix = str(tmp_path / "ck")
    params = pnet.init_params(0)
    pnet.save_checkpoint(prefix, params, epoch=1)
    # rewrite the npz with a drifted tower shape but a "fixed up" manifest
    leaves = pnet._flatten(params)
    leaves["pod_0_w"] = np.zeros((pf.F_POD + 1, leaves["pod_0_w"].shape[1]),
                                 np.float32)
    np.savez(prefix + ".npz", **leaves)
    import hashlib

    with open(prefix + ".npz", "rb") as f:
        sha = hashlib.sha256(f.read()).hexdigest()
    with open(prefix + ".json") as f:
        manifest = json.load(f)
    manifest["npz_sha256"] = sha
    with open(prefix + ".json", "w") as f:
        json.dump(manifest, f)
    with pytest.raises(pnet.CheckpointError):
        pnet.load_checkpoint(prefix)


def test_fingerprint_changes_on_param_swap(tmp_path):
    """A checkpoint swap must move the AOT fingerprint: the hash rides the
    manifest `extra`, so the store can never serve an executable built for
    different params (belt and braces — params are traced leaves)."""
    from yunikorn_tpu.aot.runtime import AotRuntime

    rt = AotRuntime(store=None, versions=("j", "jl"), backend=("cpu", 1),
                    code_version="c0")
    h1 = pnet.params_hash(pnet.init_params(0))
    h2 = pnet.params_hash(pnet.init_params(1))
    assert h1 != h2
    args = (np.zeros((4, 2), np.int32),)
    k1 = rt._key(rt.manifest("assign.solve", args, {}, ("policy", h1)))
    k1b = rt._key(rt.manifest("assign.solve", args, {}, ("policy", h1)))
    k2 = rt._key(rt.manifest("assign.solve", args, {}, ("policy", h2)))
    assert k1 == k1b
    assert k1 != k2


# ---------------------------------------------------------------------------
# N-way choose_plan fold
# ---------------------------------------------------------------------------
def test_choose_plan_n_strictly_better_challenger_wins():
    req = np.array([[4, 0], [4, 0], [4, 0]], np.int32)
    valid = np.ones(3, bool)
    greedy = np.array([0, 1, -1], np.int32)       # 2 placed
    learned = np.array([0, 0, 0], np.int32)       # 3 placed, denser
    winner, utils = pack_mod.choose_plan_n(
        [("greedy", greedy), ("learned", learned)], req, valid)
    assert winner == "learned"
    assert utils["learned"]["placed"] == 3


def test_choose_plan_n_three_plan_starvation_regression():
    """The pairwise priority guard: a learned plan that packs MORE units by
    displacing the high-priority ask must lose to BOTH other plans, and the
    pack plan that matches greedy's priority classes with more units wins
    the three-way duel."""
    #             hi  lo  lo
    priorities = np.array([10, 0, 0])
    req = np.array([[2, 0], [5, 0], [5, 0]], np.int32)
    valid = np.ones(3, bool)
    greedy = np.array([0, 1, -1], np.int32)    # hi placed, 7 units, 2 nodes
    pack = np.array([0, 1, 1], np.int32)       # hi placed, 12 units
    learned = np.array([-1, 0, 1], np.int32)   # STARVES hi for 10 units
    winner, _ = pack_mod.choose_plan_n(
        [("greedy", greedy), ("optimal", pack), ("learned", learned)],
        req, valid, priorities=priorities)
    assert winner == "optimal"
    # learned alone vs greedy: still loses despite more raw units
    winner2, _ = pack_mod.choose_plan_n(
        [("greedy", greedy), ("learned", learned)],
        req, valid, priorities=priorities)
    assert winner2 == "greedy"
    # without the guard the starving plan would have won its duel
    winner3, _ = pack_mod.choose_plan_n(
        [("greedy", greedy), ("learned", learned)], req, valid)
    assert winner3 == "learned"


def test_choose_plan_two_way_wrapper_unchanged():
    req = np.array([[3, 0], [3, 0]], np.int32)
    valid = np.ones(2, bool)
    a = np.array([0, -1], np.int32)
    b = np.array([0, 1], np.int32)
    use_pack, stats = pack_mod.choose_plan(a, b, req, valid)
    assert use_pack and stats["pack"]["placed"] == 2
    use_pack2, _ = pack_mod.choose_plan(b, b, req, valid)
    assert not use_pack2                      # tie keeps greedy


# ---------------------------------------------------------------------------
# dataset + trainer
# ---------------------------------------------------------------------------
def test_dataset_writer_roundtrip_and_cap(tmp_path):
    w = ptrain.DatasetWriter(str(tmp_path), max_cycles=2)
    ex = {
        "req": np.ones((4, 2), np.int32), "rank": np.arange(4.0),
        "valid": np.ones(4, bool), "free0": np.full((2, 2), 9, np.int32),
        "cap": np.full((2, 2), 9, np.int32), "node_ok": np.ones(2, bool),
        "priorities": np.zeros(4), "score_cols": 2, "winner": "optimal",
        "plan_greedy": np.array([0, 1, -1, 0], np.int32),
        "plan_optimal": np.array([0, 1, 1, 0], np.int32),
    }
    assert w(ex) and w(ex)
    assert not w(ex)                          # capped
    loaded = ptrain.load_dataset(str(tmp_path))
    assert len(loaded) == 2
    assert loaded[0]["winner"] == "optimal"
    assert np.array_equal(loaded[0]["plan_optimal"], ex["plan_optimal"])
    assert loaded[0]["score_cols"] == 2


def test_trainer_learns_fragmented_alignment_end_to_end(tmp_path):
    """The tentpole's round trip at test scale: record greedy-vs-pack duels
    on the fragmented two-flavor shape, fit, and the learned arm must pack
    at least as much as greedy with zero placement loss at a LARGER shape
    than it trained on (the normalized features transfer)."""
    pb = _import_policy_bench()
    w = ptrain.DatasetWriter(str(tmp_path / "ds"))
    for s in range(2):
        enc, batch, pr = pb.build(128, 64, seed=s)
        pb.record_cycle(enc, batch, pr, w)
    params, report = ptrain.fit(ptrain.load_dataset(str(tmp_path / "ds")),
                                seed=0, imitation_epochs=30,
                                finetune_epochs=20)
    assert report["examples"] == 2
    enc, batch, priorities = pb.build(192, 256, seed=77)
    n = batch.num_pods
    ga = np.asarray(solve_batch(batch, enc.nodes).assigned)[:n]
    la = np.asarray(solve_batch(batch, enc.nodes,
                                learned=(params, 1)).assigned)[:n]
    la2 = np.asarray(solve_batch(batch, enc.nodes,
                                 learned=(params, 1)).assigned)[:n]
    assert np.array_equal(la, la2)            # seeded-deterministic
    cap = np.floor(enc.nodes.capacity_arr).astype(np.int64)
    winner, utils = pack_mod.choose_plan_n(
        [("greedy", ga), ("learned", la)], batch.req.astype(np.int32),
        batch.valid, cap_i=cap, priorities=priorities)
    assert utils["learned"]["placed"] >= utils["greedy"]["placed"]
    assert utils["learned"]["units_norm"] \
        >= utils["greedy"]["units_norm"] * 0.999
    # on this shape the trained scorer should genuinely win the duel
    assert winner == "learned", utils


# ---------------------------------------------------------------------------
# supervised-ladder chaos
# ---------------------------------------------------------------------------
def test_wedged_learned_dispatch_degrades_to_greedy_without_wedging(tmp_path):
    """The ladder contract: a learned dispatch that fails every attempt
    must leave the cycle on the greedy plan (placement-identical to a
    greedy-only core) and the loop healthy for the next wave."""
    prefix = str(tmp_path / "ck")
    pnet.save_checkpoint(prefix, pnet.init_params(0), epoch=1)
    cache_l, core_l = make_core("learned", checkpoint=prefix)
    core_l.supervisor.faults.fail_forever("policy")
    placements_l = run_core_trace(core_l, cache_l)
    cache_g, core_g = make_core("greedy")
    placements_g = run_core_trace(core_g, cache_g)
    assert placements_l == placements_g
    assert len(placements_l) == 120           # both waves landed
    assert core_l.obs.get("policy_plans_total").value(outcome="failed") >= 1
    # the greedy/assign path never degraded — only the learned arm sat out
    assert not any(p.startswith("assign")
                   for p in core_l.supervisor.degraded_paths())


# ---------------------------------------------------------------------------
# conf surface
# ---------------------------------------------------------------------------
def test_conf_learned_policy_and_checkpoint_parse():
    from yunikorn_tpu.conf.schedulerconf import parse_config_map

    conf = parse_config_map({"solver.policy": "learned",
                             "solver.policyCheckpoint": "/tmp/x/ck"})
    assert conf.solver_policy == "learned"
    assert conf.solver_policy_checkpoint == "/tmp/x/ck"
    so = SolverOptions.from_conf(conf)
    assert so.policy == "learned"
    assert so.policy_checkpoint == "/tmp/x/ck"
    conf2 = parse_config_map({"solver.policy": "all"})
    assert SolverOptions.from_conf(conf2).policy == "all"
    with pytest.raises(ValueError):
        parse_config_map({"solver.policy": "sgd"})


def test_policy_all_mode_enables_both_arms():
    cache, core = make_core("all")
    assert core._pack_on() and core._learned_on()
    assert core._policy_mode() == "all"
    cache, core = make_core("optimal")
    assert core._pack_on() and not core._learned_on()


# ---------------------------------------------------------------------------
# Grafana round-17 row (pinned yunikorn_ prefix rule)
# ---------------------------------------------------------------------------
def test_grafana_round17_policy_row_prefixed():
    path = os.path.join(REPO, "deployments", "grafana-dashboard",
                        "yunikorn-tpu-dashboard.json")
    with open(path) as f:
        dash = json.load(f)
    panels = dash["panels"]
    titles = [p.get("title", "") for p in panels]
    assert any("17" in t and "row" == p.get("type")
               for t, p in zip(titles, panels)), titles
    exprs = [t.get("expr", "") for p in panels for t in p.get("targets", [])
             if "policy_" in t.get("expr", "")]
    assert any("yunikorn_policy_duels_total" in e for e in exprs)
    assert any("yunikorn_policy_inference_ms" in e for e in exprs)
    assert any("yunikorn_policy_checkpoint_epoch" in e for e in exprs)
    for p in panels:
        for t in p.get("targets", []):
            assert "yunikorn_" in t.get("expr", ""), (p.get("title"),
                                                      t.get("expr"))
