"""REST API tests: the RClient-style surface over a live scheduler
(reference helpers/yunikorn/rest_api_utils.go usage pattern).
"""
import json
import urllib.request

import pytest

from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.shim.mock_scheduler import MockScheduler
from yunikorn_tpu.webapp.rest import RestServer


@pytest.fixture
def stack():
    ms = MockScheduler()
    ms.init("")
    ms.start()
    rest = RestServer(ms.core, ms.context, port=0)
    port = rest.start()
    yield ms, port
    rest.stop()
    ms.stop()


def get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return json.loads(resp.read())


def test_health_and_queues(stack):
    ms, port = stack
    assert get(port, "/ws/v1/health")["Healthy"] is True
    queues = get(port, "/ws/v1/queues")
    assert queues["queuename"] == "root"


def test_apps_nodes_statedump(stack):
    ms, port = stack
    ms.add_node(make_node("node-1", cpu_milli=4000))
    pod = ms.add_pod(make_pod("p1", cpu_milli=500, memory=2**27,
                              labels={"applicationId": "rest-app"},
                              scheduler_name="yunikorn"))
    ms.wait_for_task_state("rest-app", pod.uid, task_mod.BOUND)
    apps = get(port, "/ws/v1/apps")
    assert apps["rest-app"]["state"] == "Running"
    nodes = get(port, "/ws/v1/nodes")
    assert nodes["node-1"]["schedulable"] is True
    dump = get(port, "/ws/v1/fullstatedump")
    assert "core" in dump and "shim" in dump
    metrics = get(port, "/ws/v1/metrics")
    assert metrics["allocation_attempt_allocated"] >= 1


def test_validate_conf_endpoint(stack):
    ms, port = stack
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/ws/v1/validate-conf",
        data=b"partitions:\n  - name: default\n    queues:\n      - name: root",
        method="POST")
    with urllib.request.urlopen(req, timeout=5) as resp:
        body = json.loads(resp.read())
    assert body["allowed"] is True
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/ws/v1/validate-conf",
        data=b"{{{bad yaml", method="POST")
    with urllib.request.urlopen(req, timeout=5) as resp:
        body = json.loads(resp.read())
    assert body["allowed"] is False


def test_webtest_proxy(stack):
    ms, port = stack
    import tempfile, os

    from yunikorn_tpu.webapp.webtest import WebTestServer

    with tempfile.TemporaryDirectory() as root:
        with open(os.path.join(root, "index.html"), "w") as f:
            f.write("<html>yunikorn</html>")
        wt = WebTestServer(root, f"http://127.0.0.1:{port}", port=0)
        wt_port = wt.start()
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{wt_port}/index.html", timeout=5) as resp:
                assert b"yunikorn" in resp.read()
            proxied = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{wt_port}/ws/v1/health", timeout=5).read())
            assert proxied["Healthy"] is True
        finally:
            wt.stop()
