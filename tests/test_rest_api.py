"""REST API tests: the RClient-style surface over a live scheduler
(reference helpers/yunikorn/rest_api_utils.go usage pattern).
"""
import json
import urllib.request

import pytest

from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.shim.mock_scheduler import MockScheduler
from yunikorn_tpu.webapp.rest import RestServer


class NullCB:
    def update_allocation(self, r): pass
    def update_application(self, r): pass
    def update_node(self, r): pass
    def predicates(self, a): return None
    def preemption_predicates(self, a): return None
    def send_event(self, e): pass
    def update_container_scheduling_state(self, r): pass
    def get_state_dump(self): return "{}"


@pytest.fixture
def stack():
    ms = MockScheduler()
    ms.init("")
    ms.start()
    rest = RestServer(ms.core, ms.context, port=0)
    port = rest.start()
    yield ms, port
    rest.stop()
    ms.stop()


def get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return json.loads(resp.read())


def test_health_and_queues(stack):
    ms, port = stack
    assert get(port, "/ws/v1/health")["Healthy"] is True
    queues = get(port, "/ws/v1/queues")
    assert queues["queuename"] == "root"


def test_apps_nodes_statedump(stack):
    ms, port = stack
    ms.add_node(make_node("node-1", cpu_milli=4000))
    pod = ms.add_pod(make_pod("p1", cpu_milli=500, memory=2**27,
                              labels={"applicationId": "rest-app"},
                              scheduler_name="yunikorn"))
    ms.wait_for_task_state("rest-app", pod.uid, task_mod.BOUND)
    apps = get(port, "/ws/v1/apps")
    assert apps["rest-app"]["state"] == "Running"
    nodes = get(port, "/ws/v1/nodes")
    assert nodes["node-1"]["schedulable"] is True
    dump = get(port, "/ws/v1/fullstatedump")
    assert "core" in dump and "shim" in dump
    metrics = get(port, "/ws/v1/metrics")
    assert metrics["allocation_attempt_allocated"] >= 1
    # recent-preemptions surface: present and well-formed (empty here —
    # nothing preempted in this stack)
    pre = get(port, "/ws/v1/preemptions")
    assert isinstance(pre["Preemptions"], list)


def test_validate_conf_endpoint(stack):
    ms, port = stack
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/ws/v1/validate-conf",
        data=b"partitions:\n  - name: default\n    queues:\n      - name: root",
        method="POST")
    with urllib.request.urlopen(req, timeout=5) as resp:
        body = json.loads(resp.read())
    assert body["allowed"] is True
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/ws/v1/validate-conf",
        data=b"{{{bad yaml", method="POST")
    with urllib.request.urlopen(req, timeout=5) as resp:
        body = json.loads(resp.read())
    assert body["allowed"] is False


def test_webtest_proxy(stack):
    ms, port = stack
    import tempfile, os

    from yunikorn_tpu.webapp.webtest import WebTestServer

    with tempfile.TemporaryDirectory() as root:
        with open(os.path.join(root, "index.html"), "w") as f:
            f.write("<html>yunikorn</html>")
        wt = WebTestServer(root, f"http://127.0.0.1:{port}", port=0)
        wt_port = wt.start()
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{wt_port}/index.html", timeout=5) as resp:
                assert b"yunikorn" in resp.read()
            proxied = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{wt_port}/ws/v1/health", timeout=5).read())
            assert proxied["Healthy"] is True
        finally:
            wt.stop()


def test_usage_trackers_and_events_endpoints():
    """Round-2 REST catalogue: per-user/group trackers + events stream
    (reference RClient usage/events APIs)."""
    import json
    import urllib.request

    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.events import get_recorder
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import (AddApplicationRequest, AllocationAsk,
                                        AllocationRequest, ApplicationRequest,
                                        NodeAction, NodeInfo, NodeRequest,
                                        RegisterResourceManagerRequest,
                                        UserGroupInfo)
    from yunikorn_tpu.core.scheduler import CoreScheduler
    from yunikorn_tpu.webapp.rest import RestServer

    yaml_text = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: default
            limits:
              - users: ["*"]
                maxresources: {vcore: 100}
"""
    cache = SchedulerCache()
    core = CoreScheduler(cache)

    core.register_resource_manager(RegisterResourceManagerRequest(
        rm_id="r", policy_group="q", config=yaml_text), NullCB())
    n = make_node("n0", cpu_milli=8000)
    cache.update_node(n)
    core.update_node(NodeRequest(nodes=[NodeInfo(node_id="n0", action=NodeAction.CREATE)]))
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="ua", queue_name="root.default",
        user=UserGroupInfo(user="alice", groups=["devs"]))]))
    p = make_pod("p0", cpu_milli=1000, memory=2**20)
    core.update_allocation(AllocationRequest(asks=[
        AllocationAsk(p.uid, "ua", get_pod_resource(p), pod=p)]))
    assert core.schedule_once() == 1
    get_recorder().eventf("Pod", "default/p0", "Normal", "Scheduled", "bound to n0")

    rest = RestServer(core, port=0)
    port = rest.start()
    try:
        def get(path):
            with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
                return json.loads(r.read())

        users = get("/ws/v1/partition/default/usage/users")
        alice = next(u for u in users if u["name"] == "alice")
        assert alice["queues"]["root.default"]["resourceUsage"].get("cpu") == 1000
        assert alice["queues"]["root.default"]["runningApplications"] == 1
        groups = get("/ws/v1/partition/default/usage/groups")
        assert any(g["name"] == "devs" for g in groups)
        events = get("/ws/v1/events/batch?count=10")
        assert any(e["reason"] == "Scheduled" for e in events["EventRecords"])
        assert get("/ws/v1/partitions") == ["default"]
    finally:
        rest.stop()


def test_step_timing_and_profile_endpoints():
    """SURVEY §5 tracing analog: per-cycle stage timing in metrics + a JAX
    profiler capture surface."""
    import json
    import urllib.request

    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import (AddApplicationRequest, AllocationAsk,
                                        AllocationRequest, ApplicationRequest,
                                        NodeAction, NodeInfo, NodeRequest,
                                        RegisterResourceManagerRequest,
                                        UserGroupInfo)
    from yunikorn_tpu.core.scheduler import CoreScheduler
    from yunikorn_tpu.webapp.rest import RestServer

    cache = SchedulerCache()
    core = CoreScheduler(cache)
    core.register_resource_manager(RegisterResourceManagerRequest(
        rm_id="r", policy_group="q"), NullCB())
    n = make_node("n0", cpu_milli=8000)
    cache.update_node(n)
    core.update_node(NodeRequest(nodes=[NodeInfo(node_id="n0", action=NodeAction.CREATE)]))
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="ta", queue_name="root.q", user=UserGroupInfo(user="u"))]))
    p = make_pod("p0", cpu_milli=500, memory=2**20)
    core.update_allocation(AllocationRequest(asks=[
        AllocationAsk(p.uid, "ta", get_pod_resource(p), pod=p)]))
    assert core.schedule_once() == 1
    lc = core.metrics["last_cycle"]["default"]
    assert lc["pods"] == 1
    assert lc["total_ms"] >= lc["solve_ms"] >= 0
    for k in ("gate_ms", "encode_ms", "solve_ms", "commit_ms", "post_ms"):
        assert lc[k] >= 0
    assert lc["at"] > 0

    rest = RestServer(core, port=0)
    port = rest.start()
    started = False
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/ws/v1/metrics") as r:
            metrics = json.loads(r.read())
        assert "last_cycle" in metrics
        # arbitrary paths rejected; only a run NAME under the base dir
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/ws/v1/profile/start?name=../../etc",
            method="POST")
        try:
            urllib.request.urlopen(req)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/ws/v1/profile/start?name=resttest",
            method="POST")
        with urllib.request.urlopen(req) as r:
            body = json.loads(r.read())
            assert body["tracing"] is True
            assert body["dir"].endswith("/resttest")
        started = True
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/ws/v1/profile/stop", method="POST")
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read())["tracing"] is False
        started = False
    finally:
        if started:  # never leak a process-global trace into later tests
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        rest.stop()


def test_rclient_waits_and_typed_gets():
    """RClient-style REST harness (reference helpers/yunikorn/rest_api_utils.go):
    typed gets + wait-for-state combinators against a live server."""
    from tests.rclient import RClient
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import (AddApplicationRequest, AllocationAsk,
                                        AllocationRequest, ApplicationRequest,
                                        NodeAction, NodeInfo, NodeRequest,
                                        RegisterResourceManagerRequest,
                                        UserGroupInfo)
    from yunikorn_tpu.core.scheduler import CoreScheduler
    from yunikorn_tpu.webapp.rest import RestServer

    cache = SchedulerCache()
    core = CoreScheduler(cache)
    core.register_resource_manager(RegisterResourceManagerRequest(
        rm_id="r", policy_group="q"), NullCB())
    rest = RestServer(core, port=0)
    port = rest.start()
    rc = RClient(port)
    try:
        rc.wait_for_health()
        n = make_node("n0", cpu_milli=8000)
        cache.update_node(n)
        core.update_node(NodeRequest(nodes=[NodeInfo(node_id="n0",
                                                     action=NodeAction.CREATE)]))
        rc.wait_for_node_count(1)
        core.update_application(ApplicationRequest(new=[AddApplicationRequest(
            application_id="rc-app", queue_name="root.q",
            user=UserGroupInfo(user="u"))]))
        p = make_pod("p0", cpu_milli=500, memory=2**20)
        core.update_allocation(AllocationRequest(asks=[
            AllocationAsk(p.uid, "rc-app", get_pod_resource(p), pod=p)]))
        core.schedule_once()
        rc.wait_for_app_state("rc-app", "Running")
        rc.wait_for_allocation_count("rc-app", 1)
        assert rc.app("rc-app")["allocations"][p.uid]["nodeId"] == "n0"
        assert rc.queues()["queuename"] == "root"
        ok = rc.validate_conf("partitions:\n  - name: default\n    queues:\n      - name: root\n")
        assert ok["allowed"] is True
        with pytest.raises(TimeoutError):
            rc.wait_for_app_state("rc-app", "Completed", timeout=0.5)
    finally:
        rest.stop()


def test_prometheus_metrics_endpoint(stack):
    """/metrics serves Prometheus text exposition (the scrape target of
    deployments/scheduler/prometheus.yml and the Grafana dashboard)."""
    ms, port = stack
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common import constants

    ms.add_node(make_node("prom-n0", cpu_milli=8000))
    pod = ms.add_pod(make_pod(
        "prom-p0", cpu_milli=200, memory=2**20,
        labels={constants.LABEL_APPLICATION_ID: "prom-app"},
        scheduler_name=constants.SCHEDULER_NAME))
    from yunikorn_tpu.cache import task as task_mod
    ms.wait_for_task_state("prom-app", pod.uid, task_mod.BOUND)

    req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
    with urllib.request.urlopen(req, timeout=5) as resp:
        ctype = resp.headers.get("Content-Type", "")
        text = resp.read().decode()
    assert ctype.startswith("text/plain")
    lines = text.splitlines()
    assert any(l.startswith("yunikorn_allocation_attempt_allocated ") for l in lines)
    assert any(l.startswith("# TYPE yunikorn_solve_count") for l in lines)
    # per-partition cycle gauges carry a partition label
    assert any(l.startswith('yunikorn_cycle_total_ms{partition="default"}')
               for l in lines)
    # every sample line parses as `name{labels} value`
    for l in lines:
        if l.startswith("#") or not l:
            continue
        name_part, _, value = l.rpartition(" ")
        float(value)
