"""bench.py dial hardening: a wedged TPU relay must never consume the
process's whole window — the dial loop honors ONE overall budget and then
concedes to a labelled CPU fallback that still produces a parsed result
(the BENCH_r05 regression: nine 150 s retries -> rc=124, parsed:null).

Driven with a FAKE DIALER + fake clock, so no relay (and no real sleeping)
is involved.
"""
import json
import os
import subprocess
import sys

import pytest

import bench


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def sleep(self, secs):
        self.now += secs


def test_wedged_relay_concedes_within_dial_window(monkeypatch):
    """Every probe wedges (consumes its full timeout). The loop must stop
    dialing once the dial window — total budget minus the CPU reserve — is
    spent, and fall back to CPU. (Attempt cap raised so this test pins the
    WINDOW bound, not the cap.)"""
    monkeypatch.setattr(bench, "TOTAL_BUDGET", 1500.0)
    monkeypatch.setattr(bench, "CPU_RESERVE", 600.0)
    monkeypatch.setenv("YK_BENCH_TPU_DIAL_ATTEMPTS", "99")
    monkeypatch.delenv("YK_BENCH_TPU_WAIT", raising=False)
    monkeypatch.delenv("YK_BENCH_FORCE_CPU", raising=False)
    clock = FakeClock()
    attempts = []

    def wedged_probe(timeout):
        attempts.append(timeout)
        clock.sleep(timeout)  # a wedged probe blocks for its whole deadline
        return None, 0, "dial timed out (fake wedge)"

    fellback = []

    def cpu_fallback():
        fellback.append(True)
        return "cpu"

    t0 = clock()
    platform = bench._init_backend_or_die(
        probe_fn=wedged_probe, clock=clock, sleep=clock.sleep,
        cpu_fallback=cpu_fallback)
    assert platform == "cpu"
    assert fellback
    elapsed = clock() - t0
    # the dial loop spent at most the dial window (1500-600) plus one
    # backoff; the CPU reserve survives for the fallback measurement
    assert elapsed <= 1500.0 - 600.0 + 60.0, (elapsed, attempts)
    assert len(attempts) >= 2  # it did retry, just inside the window
    # no single probe was allowed to stretch past the remaining window
    assert all(t <= 900.0 for t in attempts)


def test_wedged_relay_downshifts_cpu_bucket(monkeypatch):
    """The CPU fallback at TPU-bucket sizes cannot finish in the reserve:
    unpinned sizes downshift to the documented CPU bucket, pinned sizes are
    honored."""
    monkeypatch.delenv("YK_BENCH_NODES", raising=False)
    monkeypatch.delenv("YK_BENCH_PODS", raising=False)
    monkeypatch.setattr(bench, "N_NODES", 10_000)
    monkeypatch.setattr(bench, "N_PODS", 50_000)
    bench._downshift_for_cpu_fallback()
    assert (bench.N_NODES, bench.N_PODS) == (1000, 10000)
    monkeypatch.setenv("YK_BENCH_NODES", "123")
    monkeypatch.setattr(bench, "N_NODES", 123)
    bench._downshift_for_cpu_fallback()
    assert bench.N_NODES == 123      # operator-pinned size is kept


def test_dial_attempt_cap_concedes_early(monkeypatch):
    """The r01–r05 regression: 9+ dial retries consumed the driver window.
    The default attempt cap (2) must stop the loop LONG before the window
    math would, leaving the CPU reserve untouched."""
    monkeypatch.setattr(bench, "TOTAL_BUDGET", 1500.0)
    monkeypatch.setattr(bench, "CPU_RESERVE", 600.0)
    monkeypatch.delenv("YK_BENCH_TPU_DIAL_ATTEMPTS", raising=False)
    monkeypatch.delenv("YK_BENCH_TPU_WAIT", raising=False)
    monkeypatch.delenv("YK_BENCH_FORCE_CPU", raising=False)
    clock = FakeClock()
    attempts = []

    def wedged_probe(timeout):
        attempts.append(timeout)
        clock.sleep(timeout)
        return None, 0, "dial timed out (fake wedge)"

    fellback = []

    def cpu_fallback():
        fellback.append(True)
        return "cpu"

    t0 = clock()
    platform = bench._init_backend_or_die(
        probe_fn=wedged_probe, clock=clock, sleep=clock.sleep,
        cpu_fallback=cpu_fallback)
    assert platform == "cpu" and fellback
    assert len(attempts) == 2          # the default cap, not the 9+ of r05
    # two 150 s probes + two backoffs — far inside the 900 s window
    assert clock() - t0 <= 2 * 150.0 + 20.0


def test_probe_failure_then_success(monkeypatch):
    """A relay that comes back mid-window is still picked up (the fallback
    only fires after the window)."""
    monkeypatch.setenv("YK_BENCH_TPU_DIAL_ATTEMPTS", "5")
    # two wedged attempts must fit inside the hard dial wall (300 s) with
    # room for the third, successful one
    monkeypatch.setenv("YK_BENCH_TPU_DIAL_TIMEOUT", "60")
    clock = FakeClock()
    calls = []

    def flaky_probe(timeout):
        calls.append(timeout)
        if len(calls) < 3:
            clock.sleep(timeout)
            return None, 0, "wedged"
        return "cpu", 1, "ok"   # platform found (cpu stands in for tpu here)

    # the probe reports a live platform -> the parent dials in-process; the
    # in-process dial path imports jax, which in this test env is CPU
    platform = bench._init_backend_or_die(
        probe_fn=flaky_probe, clock=clock, sleep=clock.sleep,
        cpu_fallback=lambda: "cpu")
    assert platform == "cpu"
    assert len(calls) == 3


def test_bench_exits_zero_with_parsed_result_on_cpu():
    """End-to-end regression for the r5 failure: bench.py itself must exit 0
    and print one parsable JSON result line on a CPU-only box (tiny bucket,
    core mode)."""
    env = dict(os.environ)
    env.update({
        "YK_BENCH_FORCE_CPU": "1",
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "YK_BENCH_NODES": "64",
        "YK_BENCH_PODS": "256",
        "YK_BENCH_MODE": "core",
        "YK_BENCH_TOTAL_BUDGET": "240",
    })
    r = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        timeout=280, cwd=os.path.dirname(os.path.dirname(__file__)), env=env)
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    last = [l for l in r.stdout.strip().splitlines() if l.startswith("{")][-1]
    parsed = json.loads(last)
    assert parsed["unit"] == "pods/s"
    assert parsed["value"] > 0
    assert "cpu" in parsed["metric"]
    # the pressure-cycle plan latency rides every bench result (round 8)
    assert "preempt_plan_ms" in parsed
    assert parsed["preempt_plan_ms"] > 0


def test_dial_wall_cap_bounds_total_dial_time(monkeypatch):
    """The BENCH_r05 follow-up: the attempt cap must bound total dial WALL
    time too — raising the cap cannot let the dial phase stretch past
    attempts x per-dial timeout (+ slack), even inside a huge window."""
    monkeypatch.setattr(bench, "TOTAL_BUDGET", 100_000.0)
    monkeypatch.setattr(bench, "CPU_RESERVE", 600.0)
    monkeypatch.setenv("YK_BENCH_TPU_DIAL_ATTEMPTS", "3")
    monkeypatch.setenv("YK_BENCH_TPU_DIAL_TIMEOUT", "150")
    monkeypatch.delenv("YK_BENCH_TPU_WAIT", raising=False)
    monkeypatch.delenv("YK_BENCH_FORCE_CPU", raising=False)
    clock = FakeClock()
    attempts = []

    def wedged_probe(timeout):
        attempts.append(timeout)
        clock.sleep(timeout)
        return None, 0, "dial timed out (fake wedge)"

    t0 = clock()
    platform = bench._init_backend_or_die(
        probe_fn=wedged_probe, clock=clock, sleep=clock.sleep,
        cpu_fallback=lambda: "cpu")
    assert platform == "cpu"
    # 3 x 150 s probes + backoffs, bounded by the wall cap (3*150 + 60),
    # nowhere near the ~99 400 s window
    assert clock() - t0 <= 3 * 150.0 + 60.0, (clock() - t0, attempts)
    # no probe was handed a deadline past the remaining wall budget
    assert all(t <= 150.0 for t in attempts)


def test_parent_dial_wedge_emits_backend_unavailable(monkeypatch, capsys):
    """A parent dial that wedges AFTER a successful probe (the r05 rc=124
    shape: claim queue never drains) must emit the parseable
    backend-unavailable JSON and hard-exit ZERO inside the dial wall
    budget instead of waiting on the claim forever — rc 0, so the driver
    keeps the labelled row rather than losing the round to a timeout."""
    import threading

    monkeypatch.setattr(bench, "TOTAL_BUDGET", 1500.0)
    monkeypatch.setattr(bench, "CPU_RESERVE", 600.0)
    monkeypatch.setenv("YK_BENCH_TPU_DIAL_TIMEOUT", "0.05")
    monkeypatch.setenv("YK_BENCH_PARENT_DIAL_MIN", "0.2")
    # shrink the whole dial wall budget so the wedge trips in test time
    monkeypatch.setenv("YK_BENCH_TPU_WAIT", "0.5")
    monkeypatch.delenv("YK_BENCH_FORCE_CPU", raising=False)

    exited = []

    def fake_exit(code):
        exited.append(code)
        raise SystemExit(code)

    monkeypatch.setattr(bench, "_hard_exit", fake_exit)
    release = threading.Event()

    def wedged_parent_dial():
        release.wait(30)  # well past the 0.2 s dial wall minimum
        return []

    clock = FakeClock()
    with pytest.raises(SystemExit):
        bench._init_backend_or_die(
            probe_fn=lambda t: ("tpu", 1, "ok"), clock=clock,
            sleep=clock.sleep, cpu_fallback=lambda: "cpu",
            parent_dial=wedged_parent_dial)
    release.set()
    assert exited == [0]
    out = capsys.readouterr().out
    last = [l for l in out.strip().splitlines() if l.startswith("{")][-1]
    parsed = json.loads(last)
    assert parsed["metric"] == "backend-unavailable"
    assert "wedged" in parsed["error"]
    # the full key set rides the shape (drivers parse these unconditionally)
    for key in ("degradations", "slo", "topology", "aot_hits"):
        assert key in parsed


def test_hard_dial_wall_caps_attempt_math(monkeypatch):
    """The round-21 hardening: whatever the attempt cap and per-dial
    timeout multiply to, the dial phase ends at the hard wall
    (YK_BENCH_DIAL_WALL, default 300 s) — the BENCH_r04/r05 shape was
    9 attempts x 150 s = 1666 s of dialing that no other bound caught."""
    monkeypatch.setattr(bench, "TOTAL_BUDGET", 100_000.0)
    monkeypatch.setattr(bench, "CPU_RESERVE", 600.0)
    monkeypatch.setattr(bench, "DIAL_WALL", 300.0)
    monkeypatch.setenv("YK_BENCH_TPU_DIAL_ATTEMPTS", "9")
    monkeypatch.setenv("YK_BENCH_TPU_DIAL_TIMEOUT", "150")
    monkeypatch.delenv("YK_BENCH_TPU_WAIT", raising=False)
    monkeypatch.delenv("YK_BENCH_FORCE_CPU", raising=False)
    clock = FakeClock()
    attempts = []

    def wedged_probe(timeout):
        attempts.append(timeout)
        clock.sleep(timeout)
        return None, 0, "dial timed out (fake wedge)"

    t0 = clock()
    platform = bench._init_backend_or_die(
        probe_fn=wedged_probe, clock=clock, sleep=clock.sleep,
        cpu_fallback=lambda: "cpu")
    assert platform == "cpu"
    # bounded by the HARD wall (+ one backoff), not 9 x 150 s
    assert clock() - t0 <= 300.0 + 60.0, (clock() - t0, attempts)
    # and no probe was handed a deadline past the wall remainder
    assert all(t <= 300.0 for t in attempts)


def test_dial_watchdog_fires_on_real_wall_and_exits_zero(monkeypatch, capsys):
    """The real-time backstop: a dial phase wedged in a way the attempt
    math cannot see (here: a probe blocked on real wall time while the
    injected clock stands still) is ended by the watchdog, which emits the
    backend-unavailable JSON shape and exits ZERO."""
    import threading

    monkeypatch.setattr(bench, "TOTAL_BUDGET", 1500.0)
    monkeypatch.setattr(bench, "CPU_RESERVE", 600.0)
    monkeypatch.setattr(bench, "DIAL_WALL", 0.2)   # watchdog at ~0.24 s real
    monkeypatch.setenv("YK_BENCH_TPU_DIAL_ATTEMPTS", "2")
    monkeypatch.delenv("YK_BENCH_TPU_WAIT", raising=False)
    monkeypatch.delenv("YK_BENCH_FORCE_CPU", raising=False)

    tripped = threading.Event()
    exited = []

    def fake_exit(code):
        exited.append(code)
        tripped.set()          # stand-in for os._exit from the timer thread

    monkeypatch.setattr(bench, "_hard_exit", fake_exit)
    clock = FakeClock()

    def stuck_probe(timeout):
        # blocks on REAL time; the fake clock never advances, so the
        # per-attempt window math never concedes — only the watchdog can
        tripped.wait(10)
        return None, 0, "unwedged by the watchdog"

    platform = bench._init_backend_or_die(
        probe_fn=stuck_probe, clock=clock, sleep=clock.sleep,
        cpu_fallback=lambda: "cpu")
    assert platform == "cpu"   # after the (test-only) unwedge it concedes
    assert exited == [0]
    out = capsys.readouterr().out
    last = [l for l in out.strip().splitlines() if l.startswith("{")][-1]
    parsed = json.loads(last)
    assert parsed["metric"] == "backend-unavailable"
    assert "watchdog" in parsed["error"]
