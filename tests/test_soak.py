"""Soak: sustained churn must leave zero accounting drift between the three
state holders — shim cache, core queues, and encoder arrays.

The reference relies on go-deadlock + race detector for this class of bug;
here the invariants are asserted directly after a randomized workload.
"""
import random
import time

import numpy as np

from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.shim.mock_scheduler import MockScheduler


def test_churn_no_accounting_drift():
    rng = random.Random(42)
    ms = MockScheduler()
    ms.init("")
    ms.start()
    try:
        for i in range(4):
            ms.add_node(make_node(f"n{i}", cpu_milli=8000, memory=8 * 2**30))
        live = []
        counter = 0
        for step in range(30):
            # add a burst
            for _ in range(rng.randint(1, 5)):
                counter += 1
                p = ms.add_pod(make_pod(
                    f"pod-{counter}", cpu_milli=rng.choice([250, 500, 1000]),
                    memory=2**27,
                    labels={"applicationId": f"app-{counter % 3}"},
                    scheduler_name="yunikorn"))
                live.append(p)
            # complete or delete some
            rng.shuffle(live)
            for _ in range(rng.randint(0, 3)):
                if not live:
                    break
                p = live.pop()
                if rng.random() < 0.5:
                    ms.succeed_pod(p)
                else:
                    ms.delete_pod(p)
            time.sleep(0.05)

        # quiesce: wait until every live pod is terminal or bound
        deadline = time.time() + 30
        while time.time() < deadline:
            states = []
            for p in live:
                cur = ms.cluster.get_pod(p.uid)
                if cur is None or cur.is_terminated():
                    continue
                app = ms.context.get_application(p.metadata.labels["applicationId"])
                task = app.get_task(p.uid) if app else None
                states.append(task.state if task else "?")
            if all(s == task_mod.BOUND for s in states):
                break
            time.sleep(0.1)
        time.sleep(0.5)  # let the last releases settle

        # --- invariant 1: cache node aggregates == sum of their pods ---
        cache = ms.context.schedulers_cache
        for name in cache.node_names():
            info = cache.get_node(name)
            expect = {}
            for pod in info.pods.values():
                for k, v in get_pod_resource(pod).resources.items():
                    expect[k] = expect.get(k, 0) + v
            for k, v in expect.items():
                assert info.requested.get(k) == v, (name, k, info.requested.get(k), v)
            for k, v in info.requested.resources.items():
                assert v == expect.get(k, 0), (name, k, v)

        # --- invariant 2: core queue accounting == sum of app allocations ---
        total = {}
        for app in ms.core.partition.applications.values():
            for alloc in app.allocations.values():
                for k, v in alloc.resource.resources.items():
                    total[k] = total.get(k, 0) + v
        root = ms.core.queues.root
        for k in set(total) | set(root.allocated.resources):
            assert root.allocated.get(k) == total.get(k, 0), (k, root.allocated.get(k), total.get(k, 0))

        # --- invariant 3: encoder free rows == allocatable - requested ---
        ms.core.encoder.sync_nodes()
        na = ms.core.encoder.nodes
        rv = ms.core.encoder.vocabs.resources
        for name in cache.node_names():
            idx = na.index_of(name)
            info = cache.get_node(name)
            for res, slot, scale in rv.items():
                want = info.available().get(res) / scale
                assert abs(na.free[idx, slot] - want) < 1.0, (name, res, na.free[idx, slot], want)
        assert (na.free[na.valid] >= 0).all()

        # --- invariant 4: no pod double-assigned ---
        seen_nodes = {}
        for uid, node in cache.assigned_pods.items():
            assert uid not in seen_nodes
            seen_nodes[uid] = node
    finally:
        ms.stop()
