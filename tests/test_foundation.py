"""Tests for the foundation layer: fsm, locking, resource, conf, dispatcher, log.

Mirrors the reference's unit-test strategy for pkg/common, pkg/conf,
pkg/dispatcher (SURVEY.md §4 tier 1).
"""
import threading
import time

import pytest

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.events import AppEventRecord, EventRecorder, TaskEventRecord
from yunikorn_tpu.common.objects import Container, make_node, make_pod, Pod, PodSpec, ObjectMeta
from yunikorn_tpu.common.resource import (
    Resource,
    ResourceBuilder,
    get_pod_resource,
    parse_quantity,
)
from yunikorn_tpu.conf import schedulerconf as conf
from yunikorn_tpu.dispatcher.dispatcher import Dispatcher, EventType
from yunikorn_tpu.locking.locking import Mutex, RWMutex
from yunikorn_tpu.log.logger import log, resolve_level, update_logging_config
from yunikorn_tpu.utils.fsm import FSM, InvalidEventError, Transition, UnknownEventError


# ---------------------------------------------------------------------------
# FSM
# ---------------------------------------------------------------------------

def make_fsm(callbacks=None):
    return FSM(
        "New",
        [
            Transition("Submit", ["New"], "Submitted"),
            Transition("Accept", ["Submitted"], "Accepted"),
            Transition("Run", ["Accepted", "Running"], "Running"),
            Transition("Fail", ["New", "Submitted", "Accepted", "Running"], "Failed"),
        ],
        callbacks,
    )


def test_fsm_basic_transitions():
    f = make_fsm()
    assert f.current == "New"
    assert f.can("Submit")
    assert not f.can("Run")
    assert f.event("Submit") is True
    assert f.current == "Submitted"
    f.event("Accept")
    f.event("Run")
    assert f.current == "Running"
    # self-transition allowed, returns False (no state change)
    assert f.event("Run") is False


def test_fsm_invalid_and_unknown_events():
    f = make_fsm()
    with pytest.raises(InvalidEventError):
        f.event("Run")
    with pytest.raises(UnknownEventError):
        f.event("NoSuchEvent")


def test_fsm_callbacks_order():
    calls = []
    f = make_fsm(
        {
            "before_Submit": lambda e: calls.append("before"),
            "leave_New": lambda e: calls.append("leave"),
            "enter_Submitted": lambda e: calls.append("enter"),
            "enter_state": lambda e: calls.append("enter_state"),
            "after_Submit": lambda e: calls.append("after"),
        }
    )
    f.event("Submit", "arg1")
    assert calls == ["before", "leave", "enter", "enter_state", "after"]


# ---------------------------------------------------------------------------
# Locking
# ---------------------------------------------------------------------------

def test_mutex_exclusion():
    m = Mutex()
    counter = {"v": 0}

    def work():
        for _ in range(1000):
            with m:
                counter["v"] += 1

    threads = [threading.Thread(target=work) for _ in range(4)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert counter["v"] == 4000


def test_rwmutex_readers_concurrent_writers_exclusive():
    """Writers are mutually exclusive with readers and each other in BOTH
    implementations; true reader concurrency only exists in the
    detection-mode implementation (the production fast path is a single
    RLock — under the GIL pure-Python reads never run in parallel anyway,
    see locking.RWMutex docstring)."""
    from yunikorn_tpu.locking import locking as locking_mod

    rw = RWMutex()
    state = {"readers": 0, "max_readers": 0, "value": 0, "torn": False}
    lock = threading.Lock()

    def reader():
        with rw.reader():
            with lock:
                state["readers"] += 1
                state["max_readers"] = max(state["max_readers"], state["readers"])
            before = state["value"]
            time.sleep(0.005)
            if state["value"] != before:        # a writer ran under our read
                state["torn"] = True
            with lock:
                state["readers"] -= 1

    def writer():
        with rw:
            state["value"] += 1

    threads = [threading.Thread(target=reader) for _ in range(4)] + [
        threading.Thread(target=writer) for _ in range(2)
    ]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert state["value"] == 2
    assert not state["torn"]
    if locking_mod.DETECTION_ENABLED:
        assert state["max_readers"] >= 2        # instrumented impl: rw semantics
    # reader-inside-writer nesting must not deadlock on the fast path
    if not locking_mod.DETECTION_ENABLED:
        with rw:
            with rw.reader():
                pass


# ---------------------------------------------------------------------------
# Resource model
# ---------------------------------------------------------------------------

def test_parse_quantity():
    assert parse_quantity("100m", as_milli=True) == 100
    assert parse_quantity("2", as_milli=True) == 2000
    assert parse_quantity("1Gi") == 2**30
    assert parse_quantity("500M") == 500_000_000
    assert parse_quantity(4) == 4
    assert parse_quantity("1.5Gi") == int(1.5 * 2**30)
    assert parse_quantity("", as_milli=True) == 0


def test_resource_arithmetic():
    a = ResourceBuilder().cpu(1000).memory(2**30).build()
    b = ResourceBuilder().cpu(500).memory(2**29).pods(1).build()
    s = a.add(b)
    assert s.get("cpu") == 1500
    assert s.get("pods") == 1
    d = s.sub(b)
    assert d == a
    small = ResourceBuilder().cpu(500).memory(2**29).build()
    assert small.fits_in(a)
    assert not a.fits_in(small)
    assert not b.fits_in(a)  # a has no "pods" capacity


def test_get_pod_resource_sum_and_init_max():
    pod = make_pod("p1", cpu_milli=500, memory=1000)
    r = get_pod_resource(pod)
    assert r.get("cpu") == 500
    assert r.get("memory") == 1000
    assert r.get("pods") == 1

    # init container larger than container sum → max rule
    pod.spec.init_containers = [
        Container(name="init", resources_requests={"cpu": "2", "memory": "100"})
    ]
    r = get_pod_resource(pod)
    assert r.get("cpu") == 2000
    assert r.get("memory") == 1000

    # sidecar init container (restartPolicy Always) adds to the base sum
    pod.spec.init_containers.append(
        Container(name="sidecar", resources_requests={"cpu": "250m"}, restart_policy="Always")
    )
    r = get_pod_resource(pod)
    assert r.get("cpu") == 2000  # max(500+250, 2000) still init-dominated
    pod.spec.init_containers[0].resources_requests = {"cpu": "100m"}
    r = get_pod_resource(pod)
    assert r.get("cpu") == 750


# ---------------------------------------------------------------------------
# Conf
# ---------------------------------------------------------------------------

def test_conf_defaults_match_reference():
    c = conf.SchedulerConf()
    assert c.interval == 1.0
    assert c.event_channel_capacity == 1024 * 1024
    assert c.dispatch_timeout == 300.0
    assert c.kube_qps == 1000
    assert c.volume_bind_timeout == 600.0
    assert c.enable_config_hot_refresh is True
    assert c.disable_gang_scheduling is False


def test_conf_parse_and_overlay():
    flat = conf.flatten_config_maps(
        [
            {"service.schedulingInterval": "2s", "service.clusterId": "c1"},
            {"service.clusterId": "c2", "kubernetes.qps": "500"},
        ]
    )
    c = conf.parse_config_map(flat)
    assert c.cluster_id == "c2"  # override wins
    assert c.interval == 2.0
    assert c.kube_qps == 500


def test_conf_duration_parsing():
    c = conf.parse_config_map({"service.volumeBindTimeout": "1h30m"})
    assert c.volume_bind_timeout == 5400.0
    c = conf.parse_config_map({"service.volumeBindTimeout": "250ms"})
    assert c.volume_bind_timeout == 0.25


def test_conf_hot_reload_keeps_non_reloadable():
    holder = conf.ConfHolder()
    holder.update_config_maps([{"service.clusterId": "orig", "service.schedulingInterval": "5s"}], initial=True)
    holder.update_config_maps([{"service.clusterId": "changed", "service.disableGangScheduling": "true"}])
    c = holder.get()
    assert c.cluster_id == "orig"          # non-reloadable kept
    assert c.interval == 5.0               # non-reloadable kept
    assert c.disable_gang_scheduling is False  # non-reloadable kept


def test_conf_gzip_decompress():
    import gzip

    payload = gzip.compress(b"queues: {}")
    flat = conf.flatten_config_maps([{"a": "b"}], [{"queues.yaml.gz": payload}])
    assert flat["queues.yaml"] == "queues: {}"


def test_conf_queues_config_extraction():
    holder = conf.ConfHolder()
    holder.update_config_maps([{"queues.yaml": "partitions: []"}], initial=True)
    assert holder.queues_config() == "partitions: []"


# ---------------------------------------------------------------------------
# Logging
# ---------------------------------------------------------------------------

def test_log_level_inheritance():
    cfg = {"log.shim.level": "debug", "log.level": "warn"}
    import logging

    assert resolve_level("shim.cache.task", cfg) == logging.DEBUG
    assert resolve_level("core", cfg) == logging.WARNING
    update_logging_config(cfg)
    assert log("shim.cache.task").getEffectiveLevel() == logging.DEBUG
    assert log("core").getEffectiveLevel() == logging.WARNING
    update_logging_config({})  # reset


# ---------------------------------------------------------------------------
# Dispatcher
# ---------------------------------------------------------------------------

def test_dispatcher_routes_by_type_and_serializes():
    d = Dispatcher(capacity=1000)
    seen_app, seen_task = [], []
    d.register_event_handler("app", EventType.APPLICATION, lambda e: seen_app.append(e))
    d.register_event_handler("task", EventType.TASK, lambda e: seen_task.append(e))
    d.start()
    try:
        for i in range(50):
            d.dispatch(AppEventRecord(f"app-{i}", "Submit"))
            d.dispatch(TaskEventRecord("app-0", f"task-{i}", "Init"))
        assert d.drain(5)
        assert len(seen_app) == 50
        assert len(seen_task) == 50
        # order preserved (single consumer)
        assert [e.application_id for e in seen_app] == [f"app-{i}" for i in range(50)]
    finally:
        d.stop()


def test_dispatcher_async_fallback_when_full():
    d = Dispatcher(capacity=2)
    got = []
    release = threading.Event()

    def slow_handler(e):
        release.wait(5)
        got.append(e)

    d.register_event_handler("app", EventType.APPLICATION, slow_handler)
    d.start()
    try:
        for i in range(6):  # more than capacity; extras go the async path
            d.dispatch(AppEventRecord(f"app-{i}", "Submit"))
        release.set()
        assert d.drain(10)
        deadline = time.time() + 10
        while len(got) < 6 and time.time() < deadline:
            time.sleep(0.05)
        assert len(got) == 6
    finally:
        d.stop()


def test_dispatcher_not_running_raises():
    d = Dispatcher(capacity=10)
    with pytest.raises(Exception):
        d.dispatch(AppEventRecord("a", "Submit"))


# ---------------------------------------------------------------------------
# Event recorder
# ---------------------------------------------------------------------------

def test_event_recorder():
    rec = EventRecorder()
    rec.eventf("Pod", "default/p1", "Normal", "Scheduling", "app %s", "app-1")
    rec.eventf("Pod", "default/p2", "Warning", "TaskFailed", "boom")
    assert len(rec.events()) == 2
    assert rec.events(object_key="default/p1")[0].message == "app app-1"
    assert rec.events(reason="TaskFailed")[0].event_type == "Warning"


def test_constants_wire_compat():
    assert constants.CANONICAL_LABEL_APP_ID == "yunikorn.apache.org/app-id"
    assert constants.SCHEDULER_NAME == "yunikorn"
    assert constants.PLACEHOLDER_CONTAINER_IMAGE.startswith("registry.k8s.io/pause")


def test_deadlock_detection_fires(monkeypatch):
    """The reference enables go-deadlock for unit tests (Makefile:586-589);
    our locking raises DeadlockError past the timeout when enabled."""
    from yunikorn_tpu.locking import locking

    monkeypatch.setattr(locking, "DETECTION_ENABLED", True)
    monkeypatch.setattr(locking, "TIMEOUT_SECONDS", 0.2)
    m = locking.Mutex()
    m.acquire()
    with pytest.raises(locking.DeadlockError):
        m.acquire()  # same-thread re-acquire deadlocks
    m.release()

    rw = locking.RWMutex()
    rw.acquire()
    with pytest.raises(locking.DeadlockError):
        rw.r_acquire()
    rw.release()


def test_dispatcher_overflow_preserves_fifo_and_limit():
    """Round-2: overflow rides ONE retry worker (not a thread per event) and
    keeps FIFO order among overflowed events; past the async limit dispatch
    raises (reference dispatcher.go:73,176-180 semantics)."""
    d = Dispatcher(capacity=1)
    d._async_limit = 5  # shrink for the test
    got = []
    release = threading.Event()

    def slow_handler(e):
        release.wait(5)
        got.append(e.application_id)

    entered = threading.Event()

    def gate_handler(e):
        entered.set()
        slow_handler(e)

    d.register_event_handler("app", EventType.APPLICATION, gate_handler)
    d.start()
    try:
        # park the consumer inside the handler first so the queue slot is
        # deterministically occupied by the next dispatch
        d.dispatch(AppEventRecord("app-0", "Submit"))
        assert entered.wait(5)
        threads_before = threading.active_count()
        # 1 slot in queue + 5 overflow = 6 more accepted; the 7th must raise
        for i in range(1, 7):
            d.dispatch(AppEventRecord(f"app-{i}", "Submit"))
        from yunikorn_tpu.dispatcher.dispatcher import DispatchError

        with pytest.raises(DispatchError):
            d.dispatch(AppEventRecord("app-too-many", "Submit"))
        # no thread-per-event explosion (round-1 spawned one per overflow)
        assert threading.active_count() - threads_before <= 1
        release.set()
        deadline = time.time() + 10
        while len(got) < 7 and time.time() < deadline:
            time.sleep(0.05)
        assert len(got) == 7  # app-0 (parked) + app-1 (queued) + 5 overflowed
        # the overflowed events (2..6) must arrive in dispatch order
        overflowed = got[2:]
        assert overflowed == sorted(overflowed, key=lambda s: int(s.split("-")[1]))
    finally:
        d.stop()


def test_rmutex_reentrant_and_detects():
    from yunikorn_tpu.locking import locking as lk

    m = lk.RMutex()
    with m:
        with m:  # reentrant acquire must not deadlock
            pass

    # detection: a second thread times out on a held Mutex
    old_enabled, old_timeout = lk.DETECTION_ENABLED, lk.TIMEOUT_SECONDS
    lk.DETECTION_ENABLED, lk.TIMEOUT_SECONDS = True, 0.2
    try:
        m2 = lk.Mutex()
        m2.acquire()
        errs = []

        def try_acquire():
            try:
                m2.acquire()
            except lk.DeadlockError as e:
                errs.append(e)

        t = threading.Thread(target=try_acquire)
        t.start()
        t.join(5)
        assert errs, "expected DeadlockError on contended Mutex"
        m2.release()
    finally:
        lk.DETECTION_ENABLED, lk.TIMEOUT_SECONDS = old_enabled, old_timeout


@pytest.mark.slow  # ~54 s of pure XLA compiles; bucket behavior stays
# covered by the aot-store suite
def test_prewarm_buckets_compiles():
    from yunikorn_tpu.utils.jaxtools import prewarm_buckets

    results = []
    t = prewarm_buckets("64x128, bogus, 32x64", results=results)
    t.join(timeout=120)
    assert not t.is_alive()
    # bogus skipped; both valid buckets genuinely compiled
    assert results == [(64, 128, True), (32, 64, True)]
