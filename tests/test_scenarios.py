"""Brain-level scenario tests: the informer/recovery/restart corner cases the
reference pins with context_test.go + the restart_changed_config and
gang_scheduling e2e suites (VERDICT r4 item 7). Each scenario is a named test
against the full in-process scheduler (MockScheduler: real core + real shim +
FakeCluster API), asserting both behavior and the no-drift invariants.
"""
import json
import time

import pytest

from yunikorn_tpu.cache import application as app_mod
from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.shim.mock_scheduler import MockScheduler

from tests.test_context_storm import assert_no_drift, storm_pod, wait_bound


@pytest.fixture
def ms():
    m = MockScheduler()
    m.init("")
    m.start()
    yield m
    m.stop()


# ------------------------------------------------------- informer delivery


def test_duplicate_informer_deliveries(ms):
    """The same pod delivered twice (watch replay after a reconnect): one
    bind, accounting counted once — reference context_test.go duplicate-add
    scenarios."""
    ms.add_node(make_node("dup-n0", cpu_milli=8000, memory=8 * 2**30))
    pods = [storm_pod(f"dup{i}", app="dup-app", cpu=200) for i in range(10)]
    for p in pods:
        ms.add_pod(p)
        ms.add_pod(p)                      # duplicate add, same object
    assert wait_bound(ms, pods, timeout=30) == 10
    # duplicate update of the now-bound pod (resourceVersion replay)
    for p in pods:
        cur = ms.cluster.get_pod(p.uid)
        ms.cluster.update_pod(cur)
        ms.cluster.update_pod(cur)
    time.sleep(0.5)
    info = ms.context.schedulers_cache.get_node("dup-n0")
    assert info.requested.get("cpu") == 10 * 200     # counted once each
    assert_no_drift(ms)


def test_reordered_informer_deliveries(ms):
    """Update-before-add (watch events racing the lister) and delete of a
    never-seen pod: no crash, the late add still schedules — reference
    updatePod's unknown-pod path."""
    ms.add_node(make_node("ro-n0", cpu_milli=8000, memory=8 * 2**30))
    # delete of an unknown pod: must be a harmless no-op
    ghost = storm_pod("ghost", app="ro-app")
    ms.cluster.delete_pod(ghost.uid)
    # update before add: FakeCluster fires "update" for a pod the context
    # has never seen; the shim must treat it as an add
    early = storm_pod("early", app="ro-app", cpu=300)
    ms.cluster.update_pod(early)
    assert wait_bound(ms, [early], timeout=20) == 1
    assert_no_drift(ms)


def test_node_remove_readd_with_pods_in_flight(ms):
    """A node removed while pods are mid-schedule (some assumed/bound on it),
    then re-added: assumed state is cleaned, accounting rebuilt, and every
    surviving pod eventually binds — reference context node-removal handling
    plus the recovery adoption path."""
    ms.add_nodes([make_node("rr-a", cpu_milli=8000, memory=8 * 2**30),
                  make_node("rr-b", cpu_milli=8000, memory=8 * 2**30)])
    pods = [storm_pod(f"rr{i}", app="rr-app", cpu=150) for i in range(60)]
    ms.add_pods(pods)
    # yank a node while the batch is still being scheduled; pods already
    # bound there go with it (kubelet lost)
    time.sleep(0.15)
    lost = [p for p in pods
            if ms.get_pod_assignment(p) == "rr-a"]
    for p in lost:
        ms.delete_pod(p)
    ms.cluster.delete_node("rr-a")
    survivors = [p for p in pods if p not in lost]
    # the survivors must all land (on rr-b or, after re-add, rr-a again)
    time.sleep(0.3)
    ms.add_node(make_node("rr-a", cpu_milli=8000, memory=8 * 2**30))
    bound = wait_bound(ms, survivors, timeout=40)
    assert bound == len(survivors), f"{bound}/{len(survivors)} after re-add"
    time.sleep(0.5)
    assert_no_drift(ms)


# --------------------------------------------------------- config lifecycle


CONF_A = """
partitions:
  - name: default
    queues:
      - name: root
        submitacl: "*"
        queues:
          - name: qa
          - name: qb
"""

CONF_B = """
partitions:
  - name: default
    queues:
      - name: root
        submitacl: "*"
        queues:
          - name: qa
          - name: qb
            resources:
              max: {vcore: 1}
"""


def queue_pod(name, app, queue, cpu=200):
    p = storm_pod(name, app=app, cpu=cpu)
    p.metadata.labels["queue"] = queue
    return p


def test_pod_updates_racing_recovery():
    """Pod UPDATE and DELETE events landing while InitializeState is still
    replaying the pre-existing pod set (reference context_test.go
    update-during-recovery class): updates for not-yet-replayed pods must not
    duplicate tasks, deletes must not resurrect, and every surviving pod
    binds exactly once."""
    ms = MockScheduler()
    ms.init("")
    try:
        ms.add_node(make_node("ur-n0", cpu_milli=16000, memory=16 * 2**30))
        pods = [storm_pod(f"ur{i}", app="ur-app", cpu=100) for i in range(120)]
        for p in pods:
            ms.cluster.add_pod(p)          # present BEFORE the shim starts
        ms.start()                          # recovery replays them
        # immediately race the replay with updates (annotation churn) and
        # deletes of a slice of the set
        doomed = pods[::10]
        for p in pods[1::3]:
            cur = ms.cluster.get_pod(p.uid)
            if cur is not None:
                cur.metadata.annotations["touched"] = "1"
                ms.cluster.update_pod(cur)
        for p in doomed:
            ms.cluster.delete_pod(p.uid)
        survivors = [p for p in pods if p not in doomed]
        assert wait_bound(ms, survivors, timeout=40) == len(survivors)
        time.sleep(0.5)
        # deleted pods hold no core allocations at quiescence (a doomed pod
        # may legitimately have bound before its delete landed; the delete
        # must then have released the allocation — checking the CLUSTER
        # assignment would be vacuous, the pod object is gone)
        core_app = ms.core.partition.applications.get("ur-app")
        doomed_uids = {p.uid for p in doomed}
        deadline = time.time() + 10
        while core_app is not None and time.time() < deadline:
            # both allocation release AND ask removal are async — the
            # deadline must cover both or the assert below flakes
            if not (doomed_uids & set(core_app.allocations)) and \
                    not (doomed_uids & set(core_app.pending_asks)):
                break
            time.sleep(0.1)
        if core_app is not None:
            leaked = doomed_uids & set(core_app.allocations)
            assert not leaked, f"deleted pods hold allocations: {leaked}"
            asks = doomed_uids & set(core_app.pending_asks)
            assert not asks, f"deleted pods hold asks: {asks}"
        app = ms.context.get_application("ur-app")
        live = {p.uid for p in survivors}
        for task_id in list(getattr(app, "tasks", {})):
            if task_id not in live:
                task = app.get_task(task_id)
                assert task is None or task.is_terminated(), task_id
        assert_no_drift(ms)
    finally:
        ms.stop()


def test_config_hot_reload_mid_recovery():
    """A configmap update landing while InitializeState is still replaying
    pre-existing pods: the reload applies without wedging recovery and every
    replayed pod still binds."""
    ms = MockScheduler()
    ms.init(CONF_A)
    try:
        ms.add_node(make_node("hr-n0", cpu_milli=16000, memory=16 * 2**30))
        pods = [queue_pod(f"hr{i}", "hr-app", "root.qa") for i in range(50)]
        for p in pods:
            ms.cluster.add_pod(p)          # present BEFORE the shim starts
        ms.start()                          # recovery replays them
        ms.update_config(CONF_B)            # reload races the replay
        assert wait_bound(ms, pods, timeout=40) == 50
        # the reload landed: root.qb now carries its max quota
        qb = ms.core.queues.resolve("root.qb", create=False)
        assert qb is not None and qb.config.max_resource is not None
        assert_no_drift(ms)
    finally:
        ms.stop()


def test_restart_with_changed_config():
    """Scheduler restart with a DIFFERENT queue config (reference e2e
    restart_changed_config): bound pods are recovered into the new core's
    accounting, and the new config's quota governs pods submitted after the
    restart."""
    ms = MockScheduler()
    ms.init(CONF_A)
    try:
        ms.add_node(make_node("rs-n0", cpu_milli=16000, memory=16 * 2**30))
        old = [queue_pod(f"rs{i}", "rs-app", "root.qb", cpu=500)
               for i in range(8)]
        ms.add_pods(old)
        ms.start()
        assert wait_bound(ms, old, timeout=30) == 8

        ms.restart(CONF_B)
        # recovery: the 8 bound pods (4000m in root.qb) are re-registered as
        # existing allocations in the NEW core even though they exceed the
        # new 1-vcore max (running workloads are never evicted by config)
        deadline = time.time() + 20
        while time.time() < deadline:
            qb = ms.core.queues.resolve("root.qb", create=False)
            if qb is not None and qb.allocated.get("cpu") == 8 * 500:
                break
            time.sleep(0.1)
        qb = ms.core.queues.resolve("root.qb", create=False)
        assert qb is not None and qb.allocated.get("cpu") == 8 * 500
        # new pod into the over-quota queue must NOT schedule...
        blocked = queue_pod("rs-blocked", "rs-app2", "root.qb", cpu=500)
        ms.add_pod(blocked)
        time.sleep(1.5)
        assert ms.get_pod_assignment(blocked) == ""
        # ...while the unrestricted queue still flows
        ok = queue_pod("rs-ok", "rs-app3", "root.qa", cpu=500)
        ms.add_pod(ok)
        assert wait_bound(ms, [ok], timeout=20) == 1
        assert_no_drift(ms)
    finally:
        ms.stop()


# ------------------------------------------------------------------- gang


TG = [{"name": "workers", "minMember": 3,
       "minResource": {"cpu": "300m", "memory": "128Mi"}}]


def gang_pod(name, app_id, tg_name=""):
    annotations = {constants.ANNOTATION_TASK_GROUPS: json.dumps(TG)}
    if tg_name:
        annotations[constants.ANNOTATION_TASK_GROUP_NAME] = tg_name
    return make_pod(name, cpu_milli=300, memory=2**27,
                    labels={constants.LABEL_APPLICATION_ID: app_id},
                    annotations=annotations,
                    scheduler_name=constants.SCHEDULER_NAME)


def test_gang_originator_restart(ms):
    """The gang originator pod is deleted and re-created while placeholders
    hold the reservation (reference gang_scheduling_test.go:310 originator
    restart): the app keeps its gang, the new originator binds, and real
    members still replace placeholders afterwards."""
    ms.add_node(make_node("g-n0", cpu_milli=16000, memory=16 * 2**30))
    origin = gang_pod("g-driver", "gang-rs")
    ms.add_pod(origin)
    ms.wait_for_app_state("gang-rs", app_mod.RUNNING, timeout=20)
    ms.wait_for_task_state("gang-rs", origin.uid, task_mod.BOUND, timeout=20)

    # originator restarts (pod deleted + re-created with a new uid)
    ms.delete_pod(origin)
    origin2 = gang_pod("g-driver", "gang-rs")
    origin2.metadata.uid = "g-driver-take2"
    ms.add_pod(origin2)
    ms.wait_for_task_state("gang-rs", origin2.uid, task_mod.BOUND, timeout=20)

    # real members arrive and consume the gang's placeholders
    members = [gang_pod(f"g-w{i}", "gang-rs", tg_name="workers")
               for i in range(3)]
    ms.add_pods(members)
    for m in members:
        ms.wait_for_task_state("gang-rs", m.uid, task_mod.BOUND, timeout=20)
    # placeholders fully replaced
    deadline = time.time() + 15
    n_ph = lambda: sum(
        1 for p in ms.cluster.list_pods()
        if p.metadata.annotations.get(constants.ANNOTATION_PLACEHOLDER_FLAG)
        == constants.TRUE)
    while time.time() < deadline and n_ph() > 0:
        time.sleep(0.1)
    assert n_ph() == 0
    assert_no_drift(ms)


def test_gang_fifo_members_bind_in_submission_order(ms):
    """FIFO contract within a gang's task group (reference gang FIFO
    assertions): members submitted in order replace placeholders in that
    order — earlier members never wait on later ones."""
    ms.add_node(make_node("f-n0", cpu_milli=16000, memory=16 * 2**30))
    origin = gang_pod("f-driver", "gang-fifo")
    ms.add_pod(origin)
    ms.wait_for_app_state("gang-fifo", app_mod.RUNNING, timeout=20)
    members = [gang_pod(f"f-w{i}", "gang-fifo", tg_name="workers")
               for i in range(3)]
    bind_order = []
    for m in members:
        ms.add_pod(m)
        ms.wait_for_task_state("gang-fifo", m.uid, task_mod.BOUND, timeout=20)
        bind_order.append(m.uid)
    assert bind_order == [m.uid for m in members]
    assert_no_drift(ms)
