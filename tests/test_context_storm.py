"""Informer-storm tests: bursts of pod/node events through the Context at
scale, asserting the three state holders (shim cache, core queues, encoder
arrays) stay consistent — the reference covers this class with context_test.go
informer scenarios + the race detector; here the invariants are asserted
directly after each storm (VERDICT r2 weak #6: context-scale informer storms).
"""
import random
import time

import numpy as np
import pytest

from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.shim.mock_scheduler import MockScheduler


@pytest.fixture
def ms():
    m = MockScheduler()
    m.init("")
    m.start()
    yield m
    m.stop()


def storm_pod(name, app="storm-app", cpu=100, mem=2**20, **kw):
    return make_pod(name, cpu_milli=cpu, memory=mem,
                    labels={"applicationId": app}, scheduler_name="yunikorn",
                    **kw)


def assert_no_drift(ms):
    """The soak invariants, shared: node aggregates == pod sums, core queue
    accounting == app allocations, encoder free == allocatable - requested,
    no double assignment."""
    cache = ms.context.schedulers_cache
    for name in cache.node_names():
        info = cache.get_node(name)
        expect = {}
        for pod in info.pods.values():
            for k, v in get_pod_resource(pod).resources.items():
                expect[k] = expect.get(k, 0) + v
        for k, v in expect.items():
            assert info.requested.get(k) == v, (name, k, info.requested.get(k), v)
        for k, v in info.requested.resources.items():
            assert v == expect.get(k, 0), (name, k, v)

    total = {}
    for app in ms.core.partition.applications.values():
        for alloc in app.allocations.values():
            for k, v in alloc.resource.resources.items():
                total[k] = total.get(k, 0) + v
    root = ms.core.queues.root
    for k in set(total) | set(root.allocated.resources):
        assert root.allocated.get(k) == total.get(k, 0), (
            k, root.allocated.get(k), total.get(k, 0))

    ms.core.encoder.sync_nodes()
    na = ms.core.encoder.nodes
    rv = ms.core.encoder.vocabs.resources
    for name in cache.node_names():
        idx = na.index_of(name)
        if idx is None:
            continue
        info = cache.get_node(name)
        for res, slot, scale in rv.items():
            want = info.available().get(res) / scale
            assert abs(na.free[idx, slot] - want) < 1.0, (
                name, res, na.free[idx, slot], want)
    assert (na.free[na.valid] >= 0).all()

    seen = set()
    for uid in cache.assigned_pods:
        assert uid not in seen
        seen.add(uid)


def wait_bound(ms, pods, timeout=60.0, expect=None):
    """Wait until `expect` (default: all) of the given pods are bound."""
    want = len(pods) if expect is None else expect
    deadline = time.time() + timeout
    while time.time() < deadline:
        bound = sum(1 for p in pods if ms.get_pod_assignment(p))
        if bound >= want:
            return bound
        time.sleep(0.1)
    return sum(1 for p in pods if ms.get_pod_assignment(p))


def test_burst_storm_3k_pods_one_shot(ms):
    """3k pods landing as one informer burst over 64 nodes: everything binds,
    no drift — the add-path at a scale where per-event bugs compound."""
    ms.add_nodes([make_node(f"bn{i}", cpu_milli=16000, memory=32 * 2**30)
                  for i in range(64)])
    pods = [storm_pod(f"bp{i}", app=f"burst-{i % 8}") for i in range(3000)]
    ms.add_pods(pods)
    bound = wait_bound(ms, pods, timeout=90)
    assert bound == 3000, f"only {bound}/3000 bound"
    time.sleep(0.5)
    assert_no_drift(ms)


def test_node_flap_storm(ms):
    """Nodes toggling unschedulable while pods stream in: pods land only on
    schedulable capacity and the drain/restore transitions leave no drift."""
    rng = random.Random(3)
    nodes = [make_node(f"fn{i}", cpu_milli=8000, memory=8 * 2**30)
             for i in range(8)]
    ms.add_nodes(nodes)
    flapped = []
    pods = []
    for step in range(6):
        for i in range(40):
            p = storm_pod(f"fp{step}-{i}", app=f"flap-{i % 4}", cpu=150)
            pods.append(p)
            ms.add_pod(p)
        # flap two random nodes per step
        for node in rng.sample(nodes, 2):
            node.spec.unschedulable = True
            ms.cluster.update_node(node)
            flapped.append(node)
        time.sleep(0.3)
        for node in flapped:
            node.spec.unschedulable = False
            ms.cluster.update_node(node)
        flapped.clear()
    bound = wait_bound(ms, pods, timeout=60)
    assert bound == len(pods), f"only {bound}/{len(pods)} bound"
    time.sleep(0.5)
    assert_no_drift(ms)


def test_delete_pending_pods_mid_storm(ms):
    """Half the pods are deleted while still pending (a deployment scale-down
    racing the scheduler): deleted pods leave no asks behind, survivors bind."""
    # one small node: most pods stay Pending long enough to be deleted
    ms.add_node(make_node("dn0", cpu_milli=4000, memory=8 * 2**30))
    pods = [storm_pod(f"dp{i}", app="del-app", cpu=200) for i in range(200)]
    ms.add_pods(pods)
    time.sleep(0.5)                               # some bind, most pend
    doomed, survivors = pods[::2], pods[1::2]
    for p in doomed:
        ms.delete_pod(p)
    # grow capacity so the survivors can all land
    ms.add_nodes([make_node(f"dn{i}", cpu_milli=16000, memory=16 * 2**30)
                  for i in range(1, 4)])
    bound = wait_bound(ms, survivors, timeout=60)
    assert bound == len(survivors), f"only {bound}/{len(survivors)} bound"
    time.sleep(0.5)
    # no asks left for deleted pods anywhere in the core
    doomed_uids = {p.uid for p in doomed}
    for app in ms.core.partition.applications.values():
        for key in app.pending_asks:
            assert key not in doomed_uids
        # deleted-but-bound pods' allocations were released: every allocation
        # must reference a live pod
        for key in app.allocations:
            pod = ms.cluster.get_pod(key)
            assert pod is not None, f"allocation for deleted pod {key}"
    assert_no_drift(ms)


def test_node_decommission_with_bound_pods(ms):
    """Removing a node that holds bound pods (hardware failure): the node
    leaves every state holder; replacement pods land on the survivor."""
    ms.add_nodes([make_node("node-a", cpu_milli=8000, memory=8 * 2**30),
                  make_node("node-b", cpu_milli=8000, memory=8 * 2**30)])
    pods = [storm_pod(f"vp{i}", app="victim-app", cpu=500) for i in range(16)]
    ms.add_pods(pods)
    assert wait_bound(ms, pods, timeout=30) == 16
    # whichever node binpacking filled is the one that "fails"
    by_node = {}
    for p in pods:
        by_node.setdefault(ms.get_pod_assignment(p), []).append(p)
    doomed = max(by_node, key=lambda n: len(by_node[n]))
    safe = "node-a" if doomed == "node-b" else "node-b"
    # kubelet gone: pods on the node are deleted, then the node object
    for p in by_node[doomed]:
        ms.delete_pod(p)
    ms.cluster.delete_node(doomed)
    deadline = time.time() + 20
    while time.time() < deadline:
        if (ms.context.schedulers_cache.get_node(doomed) is None
                and ms.get_active_node_count_in_core() == 1):
            break
        time.sleep(0.1)
    assert ms.context.schedulers_cache.get_node(doomed) is None
    # replacements schedule onto the survivor
    repl = [storm_pod(f"rp{i}", app="victim-app", cpu=500) for i in range(8)]
    ms.add_pods(repl)
    assert wait_bound(ms, repl, timeout=30) == 8
    assert all(ms.get_pod_assignment(p) == safe for p in repl)
    time.sleep(0.5)
    assert_no_drift(ms)


def test_rapid_relabel_vocab_growth(ms):
    """Node labels churn across cycles (new vocab words force encoder repads)
    while selector-bearing pods schedule: placements stay label-correct."""
    nodes = [make_node(f"ln{i}", cpu_milli=16000, memory=16 * 2**30,
                       labels={"gen": "g0"}) for i in range(6)]
    ms.add_nodes(nodes)
    all_pods = []
    for gen in range(1, 6):
        # relabel all nodes to a NEW value (fresh vocab entry every round)
        for node in nodes:
            node.metadata.labels["gen"] = f"g{gen}"
            ms.cluster.update_node(node)
        batch = []
        for i in range(20):
            p = storm_pod(f"lp{gen}-{i}", app=f"label-app-{gen % 3}", cpu=100)
            p.spec.node_selector = {"gen": f"g{gen}"}
            batch.append(p)
        ms.add_pods(batch)
        bound = wait_bound(ms, batch, timeout=30)
        assert bound == 20, f"gen {gen}: only {bound}/20 bound"
        all_pods.extend(batch)
    # a pod selecting a retired label value must NOT schedule
    stale = storm_pod("stale", app="label-app-0", cpu=100)
    stale.spec.node_selector = {"gen": "g1"}
    ms.add_pod(stale)
    time.sleep(1.5)
    assert ms.get_pod_assignment(stale) == ""
    time.sleep(0.3)
    assert_no_drift(ms)


def test_orphan_pods_adopted_when_node_arrives(ms):
    """Pods bound to a not-yet-known node (informer ordering on recovery):
    held as orphans, adopted — with correct accounting — once the node shows
    up (reference cache orphan handling)."""
    pods = []
    for i in range(10):
        p = storm_pod(f"op{i}", app="orphan-app", cpu=300)
        p.spec.node_name = "late-node"              # already bound per API
        p.status.phase = "Running"
        pods.append(p)
        ms.add_pod(p)
    time.sleep(0.5)
    cache = ms.context.schedulers_cache
    assert cache.get_node("late-node") is None
    # node arrives; orphans must be adopted into its aggregates
    ms.add_node(make_node("late-node", cpu_milli=8000, memory=8 * 2**30))
    deadline = time.time() + 10
    while time.time() < deadline:
        info = cache.get_node("late-node")
        if info is not None and len(info.pods) == 10:
            break
        time.sleep(0.1)
    info = cache.get_node("late-node")
    assert info is not None and len(info.pods) == 10
    assert info.requested.get("cpu") == 3000
    # the occupied capacity is visible to the scheduler: a pod needing more
    # than the remainder must NOT land there
    big = storm_pod("big", app="orphan-app", cpu=6000)
    ms.add_pod(big)
    time.sleep(1.5)
    assert ms.get_pod_assignment(big) == ""
    assert_no_drift(ms)
