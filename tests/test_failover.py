"""Shard failover + crash-recovery chaos suite (robustness/failover.py +
core/shard.py quarantine/re-home/rejoin):

  * detection: a crashed loop thread (faults.InjectedCrash), a wedged loop
    (staleness past the budget) and an all-circuits-open shard are each
    diagnosed with the right reason and QUARANTINED;
  * quarantine: 100% of the dead shard's ICI domains re-home onto the
    survivors, its parked asks re-admit and place, bound pods stay bound,
    and the GlobalQuotaLedger audit stays zero-violation throughout;
  * rejoin: after the rejoin delay the shard is REBUILT from scratch and
    re-admitted at the next epoch; a wedge-recover-wedge storm leaks
    neither watchdog threads nor scheduler threads;
  * cross-shard app-COUNT limits: maxApplications exact fleet-wide through
    the ledger's app-slot reserve/confirm on the registration path, with
    guest (repair) registrations consuming no real slots;
  * the mis-eviction ledger across restart: a paid-off eviction recovered
    by a rebuilt core never reports as a mis-eviction;
  * pins: a fault-free sharded run never quarantines, and shards=1 builds
    no failover machinery at all.

The multi-second integration scenarios (wedge staleness, rejoin, the
crash-recover-crash storm, the mis-eviction restart) carry
@pytest.mark.slow: the tier-1 run sits within seconds of its wall budget,
so they ride `make failover-smoke` (which runs this file unfiltered)
instead.
"""
import threading
import time
import zlib

import pytest

from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.common.si import (
    AddApplicationRequest,
    AllocationAsk,
    AllocationRequest,
    ApplicationRequest,
    NodeAction,
    NodeInfo,
    NodeRequest,
    RegisterResourceManagerRequest,
    ResourceManagerCallback,
    UserGroupInfo,
)
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.conf.schedulerconf import parse_config_map
from yunikorn_tpu.core.scheduler import CoreScheduler
from yunikorn_tpu.core.shard import ShardedCoreScheduler, make_core_scheduler
from yunikorn_tpu.robustness.failover import (
    QUARANTINED,
    SERVING,
    FailoverOptions,
    diagnose,
)
from yunikorn_tpu.robustness.supervisor import SupervisedExecutor, SupervisorOptions
from yunikorn_tpu.shim.mock_scheduler import MockScheduler

# stale budget generous enough to absorb first-touch jit compiles on a
# loaded CPU box (a fresh shard's first admitted cycle legitimately takes
# seconds); the wedge test TIGHTENS it after warming the caches. Crash
# detection is staleness-independent (the thread is visibly dead).
FAST = FailoverOptions(stale_budget_s=12.0, probe_interval_s=0.15,
                       rejoin_after_s=1.0)


# --------------------------------------------------------------- test harness
class Recorder(ResourceManagerCallback):
    def __init__(self):
        self.new = []
        self.released = []
        self.updated = []
        self.accepted_apps = []
        self.rejected_apps = []
        self.skipped = []

    def update_allocation(self, response):
        self.new.extend(response.new)
        self.released.extend(response.released)

    def update_application(self, response):
        self.updated.extend(response.updated)
        self.accepted_apps.extend(a.application_id for a in response.accepted)
        self.rejected_apps.extend(
            (r.application_id, r.reason) for r in response.rejected)

    def update_node(self, response):
        pass

    def predicates(self, args):
        return None

    def preemption_predicates(self, args):
        return []

    def send_event(self, events):
        pass

    def update_container_scheduling_state(self, request):
        self.skipped.append(request)

    def get_state_dump(self):
        return "{}"


def _front(n=3, nodes=6, cpu=8000, start=True, options=FAST,
           config=""):
    """Direct-API sharded front end with fast failover budgets."""
    cache = SchedulerCache()
    cb = Recorder()
    front = ShardedCoreScheduler(cache, n, interval=0.03,
                                 failover_options=options)
    front.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="t", policy_group="queues",
                                      config=config), cb)
    infos = []
    for i in range(nodes):
        node = make_node(f"fn-{i}", cpu_milli=cpu)
        cache.update_node(node)
        infos.append(NodeInfo(node_id=node.name, action=NodeAction.CREATE,
                              node=node))
    front.update_node(NodeRequest(nodes=infos))
    if start:
        front.start()
    return front, cb


def _submit_app(front, app_id, tags=None):
    front.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id=app_id, queue_name="root.default",
        user=UserGroupInfo(user="alice", groups=["devs"]),
        tags=dict(tags or {}))]))


def _ask(app_id, key, cpu=500):
    pod = make_pod(key, cpu_milli=cpu, memory=2 ** 28)
    return AllocationAsk(allocation_key=key, application_id=app_id,
                         resource=get_pod_resource(pod), pod=pod)


def _wait(cond, timeout=15.0, step=0.05, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(step)
    raise AssertionError(f"timed out waiting for {msg}")


def _apps_on(front, idx):
    return [a for a, h in front._app_home.items() if h == idx]


# ------------------------------------------------------------------ detection
def test_diagnose_crashed_wedged_and_breakers():
    front, _cb = _front(n=2, nodes=2, start=False)
    try:
        core = front.shards[0]
        now = time.time()
        # not running: healthy (direct-drive test cores must not read dead)
        assert diagnose(core, now, now - 100, 1.0) is None
        core.start()
        _wait(lambda: core._thread is not None and core._thread.is_alive())
        assert diagnose(core, time.time(), time.time(), 1.0) is None
        # wedge: no completed cycle within the budget
        core._last_cycle_success_at = time.time() - 100
        assert diagnose(core, time.time(), time.time() - 200, 1.0) == "stale"
        core._last_cycle_success_at = time.time()
        # breakers: every tier of a host-ending ladder open
        sup = core.supervisor
        with sup._mu:
            sup._register_ladder("assign", ("device", "cpu", "host"))
            for tier in ("device", "cpu", "host"):
                br = sup._breaker("assign", tier)
                br.state = "open"
                br.opened_at = time.time()
        assert diagnose(core, time.time(), time.time(), 30.0) == "breakers"
        with sup._mu:
            for tier in ("device", "cpu", "host"):
                sup._breaker("assign", tier).state = "closed"
        # crashed: running flag set but the loop thread is gone
        core.stop()
        core._running.set()
        assert diagnose(core, time.time(), time.time(), 30.0) == "crashed"
        core._running.clear()
    finally:
        front.stop()


@pytest.mark.slow
def test_fault_free_sharded_run_never_quarantines():
    """The failover plane must be inert on a healthy fleet: the pre-PR
    sharded behavior is unchanged (no quarantines, every shard serving)."""
    front, cb = _front(n=3, nodes=6)
    try:
        for i in range(6):
            app = f"app-{i}"
            _submit_app(front, app)
            front.update_allocation(AllocationRequest(
                asks=[_ask(app, f"pod-{i}")]))
        _wait(lambda: len(cb.new) >= 6, msg="all pods placed")
        time.sleep(FAST.probe_interval_s * 4)
        assert front.failover.states() == {0: SERVING, 1: SERVING, 2: SERVING}
        assert front.failover.quarantines == 0
        assert front.obs.get("shard_quarantines_total").sum_over() == 0
        assert front.ledger.audit() == []
    finally:
        front.stop()


def test_injected_crash_kills_the_loop_thread():
    """faults.crash is a BaseException: no supervised handler contains it —
    the run-loop thread itself dies (the shard-death injection)."""
    front, _cb = _front(n=2, nodes=4,
                        options=FailoverOptions(enabled=False))
    try:
        core = front.shards[0]
        _wait(lambda: core._thread is not None and core._thread.is_alive())
        thread = core._thread
        core.supervisor.faults.crash("assign")
        app = next(a for a in (f"app-{i}" for i in range(32))
                   if zlib.crc32(a.encode()) % 2 == 0)
        _submit_app(front, app)
        front.update_allocation(AllocationRequest(asks=[_ask(app, "cp-0")]))
        _wait(lambda: not thread.is_alive(), msg="loop thread death")
        assert core._running.is_set()  # died, not stopped
    finally:
        front.stop()


# ----------------------------------------------------- quarantine + re-homing
def test_crash_quarantines_rehomes_and_places_parked_asks():
    front, cb = _front(n=3, nodes=6)
    try:
        victim = 1
        owned_before = front.fanout.count_for(victim)
        assert owned_before > 0
        front.shards[victim].supervisor.faults.crash("assign")
        # asks homed on the victim shard: the first triggers the crash, the
        # rest park behind the dead loop until failover re-admits them
        apps = [a for a in (f"capp-{i}" for i in range(64))
                if zlib.crc32(a.encode()) % 3 == victim][:4]
        keys = []
        for i, app in enumerate(apps):
            _submit_app(front, app)
            front.update_allocation(AllocationRequest(
                asks=[_ask(app, f"cpod-{i}")]))
            keys.append(f"cpod-{i}")
        _wait(lambda: front.failover.state(victim) == QUARANTINED,
              msg="quarantine")
        rep = front.shard_report()
        assert rep["failover"]["quarantines"] == 1
        assert rep["failover"]["last_rehome"]["shard"] == victim
        assert rep["failover"]["last_rehome"]["reason"] == "crashed"
        # 100% of its domains re-homed: the dead shard owns nothing and
        # every node is owned by a survivor
        assert front.fanout.count_for(victim) == 0
        assert rep["failover"]["rehomed_nodes_total"] == owned_before
        total_owned = sum(front.fanout.count_for(k) for k in range(3))
        assert total_owned == 6
        assert front.obs.get("shard_quarantines_total").value(
            reason="crashed") == 1
        # every parked ask re-admits on a survivor and places
        _wait(lambda: {a.allocation_key for a in cb.new} >= set(keys),
              msg="parked asks placed")
        assert front.ledger.audit() == []
        # apps re-homed off the dead shard
        assert _apps_on(front, victim) == []
    finally:
        front.stop()


@pytest.mark.slow
def test_wedge_staleness_quarantine():
    """A loop wedged INSIDE a dispatch (slow fault with a deadline too big
    to trip) completes no cycles: the stale budget catches it."""
    opts = FailoverOptions(stale_budget_s=15.0, probe_interval_s=0.15,
                           rejoin_after_s=600.0)
    front, cb = _front(n=2, nodes=4, options=opts)
    try:
        victim = 0
        # warm the jit caches first (a compile must not read as a wedge),
        # then tighten the budget and inject the real wedge
        warm_app = next(a for a in (f"warm-{i}" for i in range(64))
                        if zlib.crc32(a.encode()) % 2 == victim)
        _submit_app(front, warm_app)
        front.update_allocation(AllocationRequest(
            asks=[_ask(warm_app, "wwarm-0")]))
        _wait(lambda: any(a.allocation_key == "wwarm-0" for a in cb.new),
              timeout=60, msg="warm placement")
        front.failover.options.stale_budget_s = 1.2
        # deadline far beyond the test: the watchdog never abandons, the
        # loop thread stays stuck inside the dispatch = the true wedge
        front.shards[victim].supervisor.options.deadline_s = 3600.0
        front.shards[victim].supervisor.faults.slow(
            "assign", seconds=3600.0, times=1000)
        apps = [a for a in (f"wapp-{i}" for i in range(64))
                if zlib.crc32(a.encode()) % 2 == victim][:2]
        for i, app in enumerate(apps):
            _submit_app(front, app)
            front.update_allocation(AllocationRequest(
                asks=[_ask(app, f"wpod-{i}")]))
        _wait(lambda: front.failover.state(victim) == QUARANTINED,
              timeout=20, msg="stale quarantine")
        last = front.shard_report()["failover"]["last_event"]
        assert last["reason"] in ("stale", "breakers")
        _wait(lambda: len({a.allocation_key for a in cb.new}) >= 2,
              msg="asks placed on the survivor")
        assert front.ledger.audit() == []
    finally:
        front.stop()


def test_quarantine_preserves_bound_pods_and_ledger_usage():
    """Allocations committed by the dead shard survive: restored into the
    app's new home shard, never released, their confirmed ledger usage
    intact (audit clean), and a post-failover release still settles."""
    front, cb = _front(n=3, nodes=6)
    try:
        victim = 2
        apps = [a for a in (f"bapp-{i}" for i in range(64))
                if zlib.crc32(a.encode()) % 3 == victim][:2]
        for i, app in enumerate(apps):
            _submit_app(front, app)
            front.update_allocation(AllocationRequest(
                asks=[_ask(app, f"bpod-{i}")]))
        _wait(lambda: len(cb.new) >= 2, msg="pods bound on victim shard")
        bound_keys = {a.allocation_key for a in cb.new}
        front.quarantine_shard(victim, "manual")
        assert front.failover is not None
        # nothing released by the quarantine itself
        assert cb.released == []
        assert front.ledger.audit() == []
        # the allocations now live in each app's new home shard
        for app in apps:
            home = front._app_home[app]
            assert home != victim
            core = front.shards[home]
            with core._lock:
                capp = core.partition.applications[app]
                assert capp.allocations
                assert not capp.tags.get("yunikorn.io/shard-guest")
        # a release after failover still routes and settles the ledger
        key = sorted(bound_keys)[0]
        app_of = next(a.application_id for a in cb.new
                      if a.allocation_key == key)
        from yunikorn_tpu.common.si import AllocationRelease, TerminationType

        front.update_allocation(AllocationRequest(releases=[
            AllocationRelease(application_id=app_of, allocation_key=key,
                              termination_type=TerminationType.STOPPED_BY_RM)]))
        _wait(lambda: key not in front.ledger._use_by_key,
              msg="ledger release")
        assert front.ledger.audit() == []
    finally:
        front.stop()


def test_never_quarantines_the_last_serving_shard():
    front, _cb = _front(n=2, nodes=2, start=False)
    try:
        assert front.quarantine_shard(0, "manual") is True
        # shard 1 is the last one serving: refuse
        assert front.quarantine_shard(1, "manual") is False
        assert front.failover.state(1) == SERVING or True  # state untouched
        assert 1 not in front._quarantined
    finally:
        front.stop()


# --------------------------------------------------------------------- rejoin
@pytest.mark.slow
def test_rejoin_rebuilds_and_readmits_at_next_epoch():
    front, cb = _front(n=3, nodes=6)
    try:
        victim = 1
        old_core = front.shards[victim]
        front.shards[victim].supervisor.faults.crash("assign")
        app = next(a for a in (f"rapp-{i}" for i in range(64))
                   if zlib.crc32(a.encode()) % 3 == victim)
        _submit_app(front, app)
        front.update_allocation(AllocationRequest(asks=[_ask(app, "rp-0")]))
        _wait(lambda: front.failover.state(victim) == QUARANTINED,
              msg="quarantine")
        _wait(lambda: front.failover.state(victim) == SERVING,
              timeout=20, msg="rejoin to serving")
        # REBUILT: a fresh core object, domains flowed back at the epoch
        assert front.shards[victim] is not old_core
        assert front.fanout.count_for(victim) > 0
        assert front.epoch >= 1
        # new work homed on the rejoined shard places
        app2 = next(a for a in (f"rnew-{i}" for i in range(64))
                    if zlib.crc32(a.encode()) % 3 == victim)
        _submit_app(front, app2)
        front.update_allocation(AllocationRequest(asks=[_ask(app2, "rp-1")]))
        _wait(lambda: any(a.allocation_key == "rp-1" for a in cb.new),
              msg="post-rejoin placement")
        assert front.ledger.audit() == []
        rep = front.shard_report()
        assert rep["failover"]["rejoins"] == 1
    finally:
        front.stop()


@pytest.mark.slow
def test_crash_recover_crash_storm_leaks_no_threads():
    """The watchdog-hygiene satellite: repeated kill/rejoin cycles must not
    accumulate watchdog threads, scheduler threads or registry observers."""
    front, cb = _front(n=2, nodes=4)
    try:
        victim = 0
        hist = front.obs.get("pod_e2e_latency_seconds")

        def loop_threads():
            return sum(1 for t in threading.enumerate()
                       if t.name == "core-scheduler" and t.is_alive())

        baseline = loop_threads()
        for round_i in range(3):
            front.shards[victim].supervisor.faults.crash("assign")
            app = next(a for a in (f"sapp-{round_i}-{i}" for i in range(64))
                       if zlib.crc32(a.encode()) % 2 == victim)
            _submit_app(front, app)
            front.update_allocation(AllocationRequest(
                asks=[_ask(app, f"sp-{round_i}")]))
            _wait(lambda: front.failover.state(victim) == QUARANTINED,
                  msg=f"quarantine round {round_i}")
            _wait(lambda: front.failover.state(victim) == SERVING,
                  timeout=20, msg=f"rejoin round {round_i}")
        time.sleep(0.5)
        # no watchdog threads outlive their dispatches
        for core in front.shards:
            running, abandoned = core.supervisor.watchdog_counts()
            assert abandoned == 0
            assert running <= 1  # at most one in-flight dispatch
        # no NET loop-thread growth: each crashed loop died, each rebuild
        # started exactly one replacement (other tests' intentional wedge
        # zombies may exist in this process — only the delta is ours)
        assert loop_threads() <= baseline
        # the shared e2e histogram holds one observer per LIVE engine
        assert len(getattr(hist, "_observers", [])) <= front.n
        assert front.failover.quarantines == 3
        assert front.failover.rejoins == 3
        assert front.ledger.audit() == []
        # round-22 journal-fence pin: three fence/rebuild cycles (each
        # quarantine bumps the victim's journal epoch, each zombie drain
        # requeues) must leave the device mirror bit-equal to the ledger
        if front.usage_mirror is not None:
            assert front.usage_mirror.divergence(front.ledger) == 0
    finally:
        front.stop()


def test_watchdog_threads_gauge_tracks_abandonment():
    """Unit pin for the watchdog_threads gauge: a dispatch abandoned past
    its deadline shows state=abandoned until the wedged call returns."""
    from yunikorn_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    sup = SupervisedExecutor(SupervisorOptions(deadline_s=0.2,
                                               max_retries=0), registry=reg)
    release = threading.Event()

    def wedged():
        release.wait(10)
        return "late"

    with pytest.raises(Exception):
        sup.run("t", wedged, deadline_s=0.2)
    g = reg.get("watchdog_threads")
    assert g.value(state="abandoned") == 1
    assert sup.watchdog_counts()[1] == 1
    release.set()
    deadline = time.time() + 5
    while time.time() < deadline and sup.watchdog_counts()[1] > 0:
        time.sleep(0.02)
    assert sup.watchdog_counts() == (0, 0)
    assert g.value(state="abandoned") == 0
    assert g.value(state="running") == 0


# ------------------------------------------------- cross-shard app-COUNT caps
APPCAP_YAML = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: capped
            maxapplications: 2
          - name: default
"""


def test_app_count_limit_exact_across_shards():
    """maxApplications=2 must admit exactly 2 apps FLEET-WIDE no matter
    which shards their registrations land on (pre-ledger each shard
    enforced the cap locally: 4 shards x 2 = 8 admitted)."""
    front, cb = _front(n=4, nodes=4, start=False, config=APPCAP_YAML)
    try:
        for i in range(8):
            front.update_application(ApplicationRequest(new=[
                AddApplicationRequest(
                    application_id=f"cap-{i}", queue_name="root.capped",
                    user=UserGroupInfo(user="alice", groups=[]))]))
        front.flush()  # async delivery: registrations decide at the pumps
        homes = {front._app_home[f"cap-{i}"] for i in range(8)
                 if f"cap-{i}" in front._app_home}
        assert len(homes) > 1, "test needs apps spread over several shards"
        assert len(cb.accepted_apps) == 2
        assert len(cb.rejected_apps) == 6
        assert all("maxApplications" in reason
                   for _a, reason in cb.rejected_apps)
        # removal frees the slot for a later registration
        from yunikorn_tpu.common.si import RemoveApplicationRequest

        victim_app = cb.accepted_apps[0]
        front.update_application(ApplicationRequest(
            remove=[RemoveApplicationRequest(application_id=victim_app)]))
        front.flush()  # the remove must land before cap-late decides
        front.update_application(ApplicationRequest(new=[
            AddApplicationRequest(
                application_id="cap-late", queue_name="root.capped",
                user=UserGroupInfo(user="alice", groups=[]))]))
        front.flush()
        assert "cap-late" in cb.accepted_apps
        assert front.ledger.audit() == []
    finally:
        front.stop()


def test_guest_registration_consumes_no_app_slot():
    """A repair-path guest registration rides for free: the home shard
    already holds the app's slot, so a guest landing on a full queue must
    neither be rejected nor consume a slot."""
    front, cb = _front(n=2, nodes=2, start=False, config=APPCAP_YAML)
    try:
        for i in range(2):
            front.update_application(ApplicationRequest(new=[
                AddApplicationRequest(
                    application_id=f"g-{i}", queue_name="root.capped",
                    user=UserGroupInfo(user="alice", groups=[]))]))
        front.flush()
        assert len(cb.accepted_apps) == 2
        # deliver a GUEST registration for g-0 straight to its non-home
        # shard (what the repair pass does)
        home = front._app_home["g-0"]
        other = 1 - home
        from yunikorn_tpu.core.scheduler import SHARD_GUEST_APP_TAG

        guest = AddApplicationRequest(
            application_id="g-0", queue_name="root.capped",
            user=UserGroupInfo(user="alice", groups=[]),
            tags={SHARD_GUEST_APP_TAG: "true"})
        front.shards[other].update_application(
            ApplicationRequest(new=[guest]))
        assert ("g-0", ) not in [(a,) for a, _r in cb.rejected_apps]
        # the guest consumed nothing: a third REAL registration is still
        # rejected by the fleet-wide cap (2 slots held, not 3)
        front.update_application(ApplicationRequest(new=[
            AddApplicationRequest(
                application_id="g-late", queue_name="root.capped",
                user=UserGroupInfo(user="alice", groups=[]))]))
        front.flush()
        assert any(a == "g-late" for a, _r in cb.rejected_apps)
        st = front.ledger.stats()
        assert st["charged_keys"] == 2  # exactly two app slots held
    finally:
        front.stop()


def test_single_shard_app_count_checks_unchanged():
    """shards=1 keeps the plain local maxApplications checks (no ledger,
    no app-slot keys) — the pre-PR pin."""
    core = make_core_scheduler(SchedulerCache(), shards=1)
    assert type(core) is CoreScheduler
    assert core.quota_ledger is None
    assert not hasattr(core, "failover")


# ------------------------------------------- mis-eviction ledger over restart
@pytest.mark.slow
def test_paid_off_eviction_survives_inprocess_restart_without_misevict():
    """A preemption whose beneficiary PLACED before the restart must never
    surface as a mis-eviction after the rebuilt core recovers the bound
    pods from the API server (the _evicted_for residue is gone with the
    old core; recovery must not fabricate it)."""
    ms = MockScheduler()
    ms.init("")
    ms.start()
    try:
        ms.add_node(make_node("n1", cpu_milli=2000, memory=4 * 2 ** 30))
        low = [ms.add_pod(make_pod(f"low-{i}", cpu_milli=1000, memory=2 ** 27,
                                   labels={"applicationId": "app-low"},
                                   scheduler_name="yunikorn", priority=0))
               for i in range(2)]
        for p in low:
            ms.wait_for_task_state("app-low", p.uid, task_mod.BOUND)
        high = ms.add_pod(make_pod("high", cpu_milli=1000, memory=2 ** 27,
                                   labels={"applicationId": "app-high"},
                                   scheduler_name="yunikorn", priority=100))
        ms.wait_for_task_state("app-high", high.uid, task_mod.BOUND,
                               timeout=20)
        assert int(ms.core.obs.get("preempted_total").value()) >= 1
        assert int(ms.core.obs.get(
            "preemption_mis_evictions_total").value()) == 0
        # scheduler-pod restart: cluster (fake API server) persists
        ms.restart("")
        # run well past every preemption cooldown: if recovery fabricated
        # _evicted_for residue, the expiry sweep would count it now
        ms.core.PREEMPT_COOLDOWN_S = 0.3
        deadline = time.time() + 2.0
        while time.time() < deadline:
            ms.core.schedule_once()
            time.sleep(0.1)
        # recovered state: high still bound; ZERO mis-evictions on the
        # rebuilt core even after every cooldown expired
        assert ms.get_pod_assignment(high) == "n1"
        assert int(ms.core.obs.get(
            "preemption_mis_evictions_total").value()) == 0
        assert int(ms.core.obs.get("preempted_total").value()) == 0
    finally:
        ms.stop()


# ------------------------------------------------------------- conf + surface
def test_failover_conf_keys_parse():
    conf = parse_config_map({
        "robustness.failoverStaleSeconds": "7",
        "robustness.failoverProbeSeconds": "0.4",
        "robustness.failoverRejoinSeconds": "11",
    })
    assert conf.robustness_failover_stale_s == 7.0
    assert conf.robustness_failover_probe_s == 0.4
    assert conf.robustness_failover_rejoin_s == 11.0
    fo = FailoverOptions.from_conf(conf)
    assert (fo.stale_budget_s, fo.probe_interval_s, fo.rejoin_after_s) == \
        (7.0, 0.4, 11.0)
    assert fo.enabled is True
    off = FailoverOptions.from_conf(parse_config_map(
        {"robustness.failoverEnabled": "false"}))
    assert off.enabled is False
    with pytest.raises(ValueError):
        parse_config_map({"robustness.failoverEnabled": "maybe"})


def test_failover_metrics_and_state_gauge_exposed():
    front, _cb = _front(n=2, nodes=2, start=False)
    try:
        text = front.obs.expose()
        for series in ("shard_quarantines_total", "shard_rehome_seconds",
                       "shard_state", "watchdog_threads"):
            assert series in text, series
        g = front.obs.get("shard_state")
        assert g.value(shard="0") == 0 and g.value(shard="1") == 0
        front.quarantine_shard(0, "manual")
        # quarantine_shard called directly (not via the supervisor loop)
        # still reflects in the report through the owner's structures
        assert front.shard_report()["failover"]["rehomed_nodes_total"] >= 1
    finally:
        front.stop()


def test_grafana_round18_failover_row_prefixed():
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "deployments", "grafana-dashboard",
        "yunikorn-tpu-dashboard.json")
    with open(path) as f:
        doc = json.load(f)
    titles = [p.get("title", "") for p in doc["panels"]]
    assert any("round 18" in t.lower() or "failover" in t.lower()
               for t in titles), "round-18 failover row missing"
    exprs = []
    for p in doc["panels"]:
        for t in p.get("targets", []):
            if "expr" in t:
                exprs.append(t["expr"])
    failover_exprs = [e for e in exprs
                      if "shard_state" in e or "shard_quarantines" in e
                      or "shard_rehome" in e or "watchdog_threads" in e]
    assert failover_exprs, "failover row has no queries"
    for e in failover_exprs:
        for series in ("shard_state", "shard_quarantines_total",
                       "shard_rehome_seconds", "watchdog_threads"):
            if series in e:
                assert f"yunikorn_{series}" in e, (series, e)
