"""Solver tests: snapshot encoding + batched predicates + assignment.

Covers the reference predicate semantics (predicate_manager_test.go analog) and
the conflict-free assignment invariants: no node oversubscription, rank order
respected per node, unschedulable pods left unassigned.
"""
import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import (
    Affinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Taint,
    Toleration,
    make_node,
    make_pod,
)
from yunikorn_tpu.common.resource import Resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder


def make_env(nodes):
    cache = SchedulerCache()
    for n in nodes:
        cache.update_node(n)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return cache, enc


def ask_for(pod, cpu=100, memory=2**20, key=None):
    from yunikorn_tpu.common.resource import get_pod_resource

    return AllocationAsk(
        allocation_key=key or pod.uid,
        application_id="app-1",
        resource=get_pod_resource(pod),
        pod=pod,
    )


def names_of(enc, result, batch):
    out = {}
    assigned = np.asarray(result.assigned)
    for i, key in enumerate(batch.ask_keys):
        idx = int(assigned[i])
        out[key] = enc.nodes.name_of(idx) if idx >= 0 else None
    return out


def test_simple_fit_and_binpack():
    cache, enc = make_env([
        make_node("n1", cpu_milli=4000, memory=8 * 2**30),
        make_node("n2", cpu_milli=2000, memory=4 * 2**30),
    ])
    pods = [make_pod(f"p{i}", cpu_milli=1000, memory=2**30) for i in range(3)]
    asks = [ask_for(p) for p in pods]
    batch = enc.build_batch(asks)
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    assert all(v is not None for v in got.values())
    # no oversubscription
    free = np.asarray(res.free_after)
    assert (free >= 0).all()


def test_no_oversubscription_under_conflict():
    # one node that fits exactly 2 pods; 5 pods all want it
    cache, enc = make_env([make_node("n1", cpu_milli=2000, memory=8 * 2**30, pods=110)])
    pods = [make_pod(f"p{i}", cpu_milli=1000) for i in range(5)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    placed = [k for k, v in got.items() if v == "n1"]
    assert len(placed) == 2
    # FIFO: the first two by rank won
    assert set(placed) == {pods[0].uid, pods[1].uid}
    assert (np.asarray(res.free_after) >= 0).all()


def test_rank_orders_scarce_capacity():
    cache, enc = make_env([make_node("n1", cpu_milli=1000)])
    pods = [make_pod(f"p{i}", cpu_milli=1000) for i in range(3)]
    # rank: p2 first
    batch = enc.build_batch([ask_for(p) for p in pods], ranks=[3.0, 2.0, 1.0])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    assert got[pods[2].uid] == "n1"
    assert got[pods[0].uid] is None and got[pods[1].uid] is None


def test_node_selector():
    cache, enc = make_env([
        make_node("gpu-node", labels={"accelerator": "tpu"}),
        make_node("plain-node"),
    ])
    pod = make_pod("p1", cpu_milli=100, node_selector={"accelerator": "tpu"})
    batch = enc.build_batch([ask_for(pod)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[pod.uid] == "gpu-node"


def test_node_selector_no_match():
    cache, enc = make_env([make_node("n1", labels={"zone": "a"})])
    pod = make_pod("p1", cpu_milli=100, node_selector={"zone": "b"})
    batch = enc.build_batch([ask_for(pod)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[pod.uid] is None


def test_taints_and_tolerations():
    taint = Taint(key="dedicated", value="batch", effect="NoSchedule")
    cache, enc = make_env([
        make_node("tainted", taints=[taint], cpu_milli=16000),
        make_node("open", cpu_milli=100),  # tiny: forces toleration check to matter
    ])
    # intolerant pod: cannot land on tainted; fits on open
    p1 = make_pod("intolerant", cpu_milli=50)
    # tolerant pod: Equal match
    p2 = make_pod("tolerant", cpu_milli=4000)
    p2.spec.tolerations = [Toleration(key="dedicated", operator="Equal", value="batch", effect="NoSchedule")]
    # exists-key toleration
    p3 = make_pod("exists-tol", cpu_milli=4000)
    p3.spec.tolerations = [Toleration(key="dedicated", operator="Exists")]
    batch = enc.build_batch([ask_for(p) for p in (p1, p2, p3)])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    assert got[p1.uid] == "open"
    assert got[p2.uid] == "tainted"
    assert got[p3.uid] == "tainted"


def test_node_affinity_in_and_notin():
    cache, enc = make_env([
        make_node("a1", labels={"zone": "a"}),
        make_node("b1", labels={"zone": "b"}),
        make_node("c1", labels={"zone": "c"}),
    ])
    # In with multiple values (any-of path)
    p1 = make_pod("multi-in", cpu_milli=100)
    p1.spec.affinity = Affinity(node_required_terms=[
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("zone", "In", ["a", "b"])])
    ])
    # NotIn
    p2 = make_pod("notin", cpu_milli=100)
    p2.spec.affinity = Affinity(node_required_terms=[
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("zone", "NotIn", ["a", "b"])])
    ])
    # Exists
    p3 = make_pod("exists", cpu_milli=100)
    p3.spec.affinity = Affinity(node_required_terms=[
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("zone", "Exists", [])])
    ])
    batch = enc.build_batch([ask_for(p) for p in (p1, p2, p3)])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    assert got[p1.uid] in ("a1", "b1")
    assert got[p2.uid] == "c1"
    assert got[p3.uid] in ("a1", "b1", "c1")


def test_affinity_or_terms():
    cache, enc = make_env([
        make_node("a1", labels={"zone": "a"}),
        make_node("b1", labels={"disk": "ssd"}),
    ])
    pod = make_pod("or-terms", cpu_milli=100)
    pod.spec.affinity = Affinity(node_required_terms=[
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("zone", "In", ["zzz"])]),
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("disk", "In", ["ssd"])]),
    ])
    batch = enc.build_batch([ask_for(pod)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[pod.uid] == "b1"


def test_gt_host_fallback():
    cache, enc = make_env([
        make_node("small", labels={"cores": "8"}),
        make_node("big", labels={"cores": "64"}),
    ])
    pod = make_pod("gt", cpu_milli=100)
    pod.spec.affinity = Affinity(node_required_terms=[
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("cores", "Gt", ["16"])])
    ])
    batch = enc.build_batch([ask_for(pod)])
    assert batch.g_host_mask is not None
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[pod.uid] == "big"


def test_host_port_conflict():
    cache, enc = make_env([make_node("n1"), make_node("n2")])
    # existing pod occupies port 8080 on n1
    occupant = make_pod("occupant", cpu_milli=100, node_name="n1", phase="Running")
    occupant.spec.containers[0].ports = [{"hostPort": 8080, "protocol": "TCP"}]
    cache.update_pod(occupant)
    enc.sync_nodes()
    pod = make_pod("wants-8080", cpu_milli=100)
    pod.spec.containers[0].ports = [{"hostPort": 8080, "protocol": "TCP"}]
    batch = enc.build_batch([ask_for(pod)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[pod.uid] == "n2"


def test_unschedulable_node_excluded():
    cache, enc = make_env([
        make_node("cordoned", unschedulable=True),
        make_node("ready"),
    ])
    pod = make_pod("p", cpu_milli=100)
    batch = enc.build_batch([ask_for(pod)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[pod.uid] == "ready"


def test_incremental_capacity_update():
    cache, enc = make_env([make_node("n1", cpu_milli=2000)])
    # occupy half via the cache (simulates informer-observed pod)
    occupant = make_pod("occ", cpu_milli=1000, node_name="n1", phase="Running")
    cache.update_pod(occupant)
    enc.sync_nodes()  # only dirty node re-encoded
    p = make_pod("p", cpu_milli=1500)
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[p.uid] is None  # only 1000m free
    p2 = make_pod("p2", cpu_milli=900)
    batch = enc.build_batch([ask_for(p2)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[p2.uid] == "n1"


def test_large_batch_many_nodes():
    nodes = [make_node(f"n{i}", cpu_milli=16000, memory=16 * 2**30, pods=110) for i in range(64)]
    cache, enc = make_env(nodes)
    pods = [make_pod(f"p{i}", cpu_milli=500, memory=2**28) for i in range(500)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes, chunk=128)
    got = names_of(enc, res, batch)
    assert all(v is not None for v in got.values())
    free = np.asarray(res.free_after)
    assert (free >= 0).all()
    # per-node pod count <= 110
    counts = {}
    for v in got.values():
        counts[v] = counts.get(v, 0) + 1
    assert max(counts.values()) <= 110


def test_binpacking_prefers_packed_node():
    cache, enc = make_env([
        make_node("empty", cpu_milli=16000),
        make_node("half", cpu_milli=16000),
    ])
    occ = make_pod("occ", cpu_milli=8000, node_name="half", phase="Running")
    cache.update_pod(occ)
    enc.sync_nodes()
    p = make_pod("p", cpu_milli=1000)
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes, policy="binpacking")
    assert names_of(enc, res, batch)[p.uid] == "half"
    res = solve_batch(batch, enc.nodes, policy="spread")
    assert names_of(enc, res, batch)[p.uid] == "empty"


def test_prefer_no_schedule_taint_scores_lower():
    """PreferNoSchedule taints don't filter but push pods elsewhere; when only
    the soft-tainted node remains feasible, pods still land there."""
    soft = Taint(key="maint", value="soon", effect="PreferNoSchedule")
    cache, enc = make_env([
        make_node("soft-tainted", taints=[soft], cpu_milli=16000),
        make_node("clean", cpu_milli=16000),
    ])
    pods = [make_pod(f"p{i}", cpu_milli=1000) for i in range(4)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    assert all(v == "clean" for v in got.values())
    # saturate the clean node → overflow goes to the soft-tainted one
    big = [make_pod(f"big{i}", cpu_milli=7000) for i in range(3)]
    batch = enc.build_batch([ask_for(p) for p in big])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    assert sorted(v for v in got.values()) == ["clean", "clean", "soft-tainted"]


def test_soft_taint_tolerated_no_penalty():
    soft = Taint(key="maint", value="soon", effect="PreferNoSchedule")
    cache, enc = make_env([
        make_node("soft-tainted", taints=[soft], cpu_milli=4000),
        make_node("clean", cpu_milli=4000),
    ])
    # make the tainted node clearly fuller so binpacking prefers it iff the
    # taint is tolerated (no penalty)
    occ = make_pod("occ", cpu_milli=3000, node_name="soft-tainted", phase="Running")
    cache.update_pod(occ)
    enc.sync_nodes()
    p = make_pod("tol", cpu_milli=500)
    p.spec.tolerations = [Toleration(key="maint", operator="Equal", value="soon",
                                     effect="PreferNoSchedule")]
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[p.uid] == "soft-tainted"
    # the same pod without the toleration avoids the tainted node
    p2 = make_pod("intol", cpu_milli=500)
    batch = enc.build_batch([ask_for(p2)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[p2.uid] == "clean"


def test_preferred_node_affinity_scoring():
    cache, enc = make_env([
        make_node("ssd-node", labels={"disk": "ssd"}),
        make_node("hdd-node", labels={"disk": "hdd"}),
    ])
    p = make_pod("wants-ssd", cpu_milli=100)
    p.spec.affinity = Affinity(node_preferred_terms=[
        (100, NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement("disk", "In", ["ssd"])]))])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[p.uid] == "ssd-node"
    # NotIn preference pushes away
    p2 = make_pod("avoids-hdd", cpu_milli=100)
    p2.spec.affinity = Affinity(node_preferred_terms=[
        (100, NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement("disk", "NotIn", ["hdd"])]))])
    batch = enc.build_batch([ask_for(p2)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[p2.uid] == "ssd-node"


# ---------------------------------------------------------------------------
# Round-2: exact handling of constraints the tensors can't hold
# (reference never approximates a predicate, predicate_manager.go:202-250)
# ---------------------------------------------------------------------------

def test_nine_or_terms_exact():
    """More OR-terms than MAX_TERMS (8): the 9th term must still be honored
    exactly via the host path (round-1 truncated it silently)."""
    nodes = [make_node(f"n{i}", labels={"shard": f"s{i}"}) for i in range(10)]
    cache, enc = make_env(nodes)
    p = make_pod("picky", cpu_milli=100, memory=2**20)
    # 9 OR terms, each matching exactly one shard; only shards s8 and s0 exist
    # with capacity... use terms s1..s9 but only node n9 carries shard s9 and
    # nodes n1..n8 are made unschedulable to force the 9th term to decide
    p.spec.affinity = Affinity(node_required_terms=[
        NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement("shard", "In", [f"s{i}"])])
        for i in range(1, 10)
    ])
    for i in range(1, 9):
        nodes[i].spec.unschedulable = True
        cache.update_node(nodes[i])
    enc.sync_nodes(full=True)
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    # n0 (shard s0) matches NO term; n9 (shard s9) matches term 9 → must pick n9
    assert got[p.uid] == "n9"


def test_gt_expr_inside_multi_term_or_is_not_anded():
    """A Gt expression in term A must not be ANDed over term B's matches:
    a node satisfying only B stays feasible (round-1 host_exprs bug)."""
    cache, enc = make_env([
        make_node("small-ssd", labels={"disk": "ssd", "mem-gb": "8"}),
    ])
    p = make_pod("either", cpu_milli=100, memory=2**20)
    p.spec.affinity = Affinity(node_required_terms=[
        # term A: mem-gb > 100 (small-ssd fails this)
        NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement("mem-gb", "Gt", ["100"])]),
        # term B: disk ssd (small-ssd satisfies this)
        NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement("disk", "In", ["ssd"])]),
    ])
    batch = enc.build_batch([ask_for(p)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[p.uid] == "small-ssd"


def test_multi_value_preferred_in_scores_all_values():
    """preferred In [a, b]: a zone-b node must receive the bonus too
    (round-1 approximated by the first value only)."""
    cache, enc = make_env([
        make_node("nb", labels={"zone": "b"}),
        make_node("nc", labels={"zone": "c"}),
    ])
    p = make_pod("prefers", cpu_milli=100, memory=2**20)
    p.spec.affinity = Affinity(node_preferred_terms=[
        (100, NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement("zone", "In", ["a", "b"])])),
    ])
    batch = enc.build_batch([ask_for(p)])
    assert batch.g_host_soft is not None
    res = solve_batch(batch, enc.nodes, policy="spread")
    # zone-b matches the preference; zone-c does not → must pick nb
    assert names_of(enc, res, batch)[p.uid] == "nb"


def test_preferred_term_overflow_host_scored():
    """A 5th preferred term (> MAX_PREF_TERMS=4) still contributes score."""
    cache, enc = make_env([
        make_node("plain"),
        make_node("gold", labels={"tier": "gold"}),
    ])
    p = make_pod("wants-gold", cpu_milli=100, memory=2**20)
    terms = [(1, NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement(f"never{i}", "In", [f"x{i}"])])) for i in range(4)]
    terms.append((100, NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("tier", "In", ["gold"])])))
    p.spec.affinity = Affinity(node_preferred_terms=terms)
    batch = enc.build_batch([ask_for(p)])
    assert batch.g_host_soft is not None
    res = solve_batch(batch, enc.nodes, policy="spread")
    assert names_of(enc, res, batch)[p.uid] == "gold"


# ---------------------------------------------------------------------------
# Round-2: vocab growth / repad paths (reference has fixed Go types; the
# tensor encoding must stay exact across label/taint word-boundary growth)
# ---------------------------------------------------------------------------

def test_label_vocab_growth_past_word_boundary():
    """Start with a tiny label vocab, then add nodes/pods referencing >32
    distinct label values (crosses the uint32 word boundary): selectors must
    still match exactly after the repad."""
    cache, enc = make_env([make_node("seed", labels={"zone": "a"})])
    p0 = make_pod("p0", cpu_milli=100, memory=2**20, node_selector={"zone": "a"})
    batch = enc.build_batch([ask_for(p0)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[p0.uid] == "seed"
    words_before = enc.vocabs.labels.num_words
    # grow: 130 new nodes each with a distinct label value — enough bits to
    # outgrow the initial padded word width and force a node-array repad
    for i in range(130):
        cache.update_node(make_node(f"g{i}", labels={"shard": f"s{i}"}))
    enc.sync_nodes()
    assert enc.vocabs.labels.num_words > words_before  # repad actually happened
    # selector for a value interned AFTER the boundary crossing
    p1 = make_pod("p1", cpu_milli=100, memory=2**20,
                  node_selector={"shard": "s127"})
    p2 = make_pod("p2", cpu_milli=100, memory=2**20, node_selector={"zone": "a"})
    batch = enc.build_batch([ask_for(p1), ask_for(p2)])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    assert got[p1.uid] == "g127"
    assert got[p2.uid] == "seed"  # pre-growth bit still matches post-repad


def test_taint_vocab_growth_invalidates_cached_groups():
    """A cached group spec with an Exists toleration must re-encode when the
    taint vocab grows, or it would not tolerate the new taint."""
    cache, enc = make_env([
        make_node("t0", taints=[Taint("a", "1", "NoSchedule")]),
    ])
    tol_all = make_pod("tol0", cpu_milli=100, memory=2**20)
    tol_all.spec.tolerations = [Toleration(operator="Exists")]
    batch = enc.build_batch([ask_for(tol_all)])
    res = solve_batch(batch, enc.nodes)
    assert names_of(enc, res, batch)[tol_all.uid] == "t0"
    # new node with a brand-new taint key (vocab grows); same group signature
    cache.update_node(make_node("t1", cpu_milli=32000,
                                taints=[Taint("brand-new", "x", "NoSchedule")]))
    enc.sync_nodes()
    tol_b = make_pod("tol1", cpu_milli=100, memory=2**20)
    tol_b.spec.tolerations = [Toleration(operator="Exists")]
    # fill t0 COMPLETELY so only t1 can host tol_b: the cached Exists spec
    # must have re-encoded to tolerate the NEW taint or tol_b goes unplaced
    filler = make_pod("filler", cpu_milli=16000, memory=2**20)
    filler.spec.tolerations = [Toleration(operator="Exists")]
    batch = enc.build_batch([ask_for(filler), ask_for(tol_b)])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    assert got[filler.uid] == "t0"
    assert got[tol_b.uid] == "t1"


def test_resource_vocab_growth_restarts_batch():
    """A pod asking for resource names never seen before (extended resources)
    grows the resource vocab past the padded row width mid-encode; build_batch
    must restart wider and still solve correctly."""
    cache, enc = make_env([make_node("plain", cpu_milli=8000)])
    r_before = enc.vocabs.resources.num_slots
    # more NEW resource names than free padded slots → quantize_request grows
    # the vocab past R and the `row.shape[0] > R` restart path fires
    extras = {f"example.com/dev{i}": 1 for i in range(r_before + 1)}
    gpu_node = make_node("gpu-node", cpu_milli=8000, extra_resources=dict(extras))
    cache.update_node(gpu_node)
    # deliberately NOT syncing first: the ask interns the new names mid-encode
    p = make_pod("wants", cpu_milli=100, memory=2**20,
                 extra_resources=dict(extras))
    plain_pod = make_pod("plain-pod", cpu_milli=100, memory=2**20)
    batch = enc.build_batch([ask_for(p), ask_for(plain_pod)])
    assert enc.vocabs.resources.num_slots > r_before  # grew past the old pad
    assert batch.req.shape[1] == enc.vocabs.resources.num_slots
    enc.sync_nodes()
    batch = enc.build_batch([ask_for(p), ask_for(plain_pod)])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    assert got[p.uid] == "gpu-node"
    assert got[plain_pod.uid] is not None


def test_water_fill_no_int32_overflow_at_cluster_scale():
    """Cluster-wide free capacity past 2^31 device units (e.g. 10k x 256GiB
    in MiB units) must not wrap the water-fill's prefix sums: the saturating
    scan keeps cumF monotone, so proposals stay valid and the batch still
    lands in few rounds (round-3 regression: a plain int32 cumsum wrapped
    negative and broke searchsorted's precondition)."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.ops.assign import solve_batch
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for i in range(64):
        # 2^48 bytes = 2^28 MiB units each; 64 nodes -> 2^34 total (wraps i32)
        cache.update_node(make_node(f"n{i}", cpu_milli=64000, memory=2**48))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"p{i}", cpu_milli=500, memory=2**30) for i in range(256)]
    asks = [AllocationAsk(p.uid, "a", get_pod_resource(p), pod=p) for p in pods]
    batch = enc.build_batch(asks)
    res = solve_batch(batch, enc.nodes, chunk=256)
    a = np.asarray(res.assigned)[: batch.num_pods]
    assert (a >= 0).all()
    assert (np.asarray(res.free_after) >= 0).all()
    # water-fill (not 16 rounds of argmax fallback) must have done the work
    assert int(res.rounds) <= 4


def test_intra_batch_host_port_exclusivity():
    """Two pods in ONE batch wanting the same hostPort must land on different
    nodes (caught by the differential fuzzer: the static port mask only sees
    existing pods; the synthetic capacity-1 port columns enforce this)."""
    cache, enc = make_env([make_node("pn1"), make_node("pn2"),
                           make_node("pn3")])
    pods = []
    for i in range(3):
        p = make_pod(f"web-{i}", cpu_milli=100)
        p.spec.containers[0].ports = [{"hostPort": 8443, "protocol": "TCP"}]
        pods.append(p)
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes)
    got = names_of(enc, res, batch)
    placed = [v for v in got.values() if v is not None]
    assert len(placed) == 3                    # 3 ports, 3 nodes: all fit
    assert len(set(placed)) == 3               # each on its own node

    # a 4th same-port pod has nowhere to go
    extra = make_pod("web-3", cpu_milli=100)
    extra.spec.containers[0].ports = [{"hostPort": 8443, "protocol": "TCP"}]
    batch2 = enc.build_batch([ask_for(p) for p in pods + [extra]])
    res2 = solve_batch(batch2, enc.nodes)
    got2 = names_of(enc, res2, batch2)
    assert sum(1 for v in got2.values() if v is not None) == 3


def test_cross_cycle_port_exclusivity_via_ports_delta():
    """An in-flight allocation's hostPort (committed last cycle, assume not
    yet visible in the cache) must block a same-port pod this cycle — the
    ports_delta overlay, the port analog of free_delta."""
    import numpy as np

    cache, enc = make_env([make_node("cn1", cpu_milli=8000)])
    from yunikorn_tpu.snapshot.vocab import port_bit

    p1 = make_pod("held", cpu_milli=100)
    p1.spec.containers[0].ports = [{"hostPort": 9090, "protocol": "TCP"}]
    # cycle 1 encoded p1 (interns the port bit) and committed it to cn1
    enc.build_batch([ask_for(p1)])
    b = enc.vocabs.ports.lookup(port_bit("TCP", 9090))
    assert b >= 0
    delta = np.zeros((enc.nodes.capacity, enc.vocabs.ports.num_words), np.uint32)
    idx = enc.nodes.index_of("cn1")
    delta[idx, b // 32] |= np.uint32(1 << (b % 32))

    p2 = make_pod("wants-same", cpu_milli=100)
    p2.spec.containers[0].ports = [{"hostPort": 9090, "protocol": "TCP"}]
    batch = enc.build_batch([ask_for(p2)])
    res = solve_batch(batch, enc.nodes, ports_delta=delta)
    assert names_of(enc, res, batch)[p2.uid] is None      # port held in-flight
    res2 = solve_batch(batch, enc.nodes)                   # without the overlay
    assert names_of(enc, res2, batch)[p2.uid] == "cn1"


# ---------------------------------------------------------------- chunk chain
# Batches above solve_batch's max_batch run as chained fixed-shape chunk
# solves (capacity + locality-count carry) so only the canonical bucket ever
# compiles (the r3 TPU capture paid ~408s compiling the monolithic 65536-pod
# shape through the relay — VERDICT r3 item 2). These tests force a tiny
# max_batch so the chain is exercised at unit-test cost.

def test_chunked_chain_matches_single_solve_commitments():
    """Chained chunk solves must place everything a single solve places, with
    no node oversubscription — capacity carried across chunks."""
    nodes = [make_node(f"ch{i}", cpu_milli=4000, memory=8 * 2**30)
             for i in range(8)]
    cache, enc = make_env(nodes)
    pods = [make_pod(f"cp{i}", cpu_milli=200, memory=2**28) for i in range(160)]
    asks = [ask_for(p) for p in pods]
    batch = enc.build_batch(asks)
    single = solve_batch(batch, enc.nodes)
    chained = solve_batch(batch, enc.nodes, max_batch=64)   # 256-pod bucket → 4 chunks
    got_single = names_of(enc, single, batch)
    got_chained = names_of(enc, chained, batch)
    assert sum(1 for v in got_single.values() if v) == 160
    assert sum(1 for v in got_chained.values() if v) == 160
    assert (np.asarray(chained.free_after) >= 0).all()
    # per-node totals stay within capacity (exact bookkeeping check)
    used = {}
    for key, node in got_chained.items():
        used[node] = used.get(node, 0) + 200
    assert all(v <= 4000 for v in used.values())


def test_chunked_chain_respects_capacity_exhaustion():
    """Later chunks must see capacity consumed by earlier chunks: 30 pods of
    1000m against 2 nodes x 8000m → exactly 16 place, 14 stay unassigned."""
    cache, enc = make_env([
        make_node("cx1", cpu_milli=8000, memory=64 * 2**30),
        make_node("cx2", cpu_milli=8000, memory=64 * 2**30),
    ])
    pods = [make_pod(f"xp{i}", cpu_milli=1000, memory=2**20) for i in range(30)]
    batch = enc.build_batch([ask_for(p) for p in pods])
    res = solve_batch(batch, enc.nodes, max_batch=64)  # N pads to 64 ≥ bucket
    # force multiple chunks regardless of padding: re-run with the smallest cap
    batch2 = enc.build_batch([ask_for(p) for p in pods], min_batch=128)
    res2 = solve_batch(batch2, enc.nodes, max_batch=64)   # 128-pod bucket → 2 chunks
    for r, b in ((res, batch), (res2, batch2)):
        got = names_of(enc, r, b)
        placed = sum(1 for v in got.values() if v)
        assert placed == 16, placed
        assert (np.asarray(r.free_after) >= 0).all()


def test_chunked_chain_carries_locality_counts():
    """A hard topology-spread group split across chunks must carry its domain
    counts: without the carry, chunk 2 re-seeds counts from the (empty) cache
    and the final zone skew would exceed maxSkew."""
    from yunikorn_tpu.common.objects import TopologySpreadConstraint

    nodes = []
    for z in range(4):
        for i in range(2):
            n = make_node(f"z{z}n{i}", cpu_milli=64000, memory=64 * 2**30)
            n.metadata.labels["zone"] = f"zone-{z}"
            nodes.append(n)
    cache, enc = make_env(nodes)
    pods = []
    for i in range(96):
        p = make_pod(f"sp{i}", cpu_milli=100, memory=2**20)
        p.metadata.labels["spread"] = "1"
        p.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=1, topology_key="zone", when_unsatisfiable="DoNotSchedule",
            label_selector={"matchLabels": {"spread": "1"}})]
        pods.append(p)
    batch = enc.build_batch([ask_for(p) for p in pods], min_batch=128)
    res = solve_batch(batch, enc.nodes, max_batch=32)      # 128-bucket → 4 chunks
    got = names_of(enc, res, batch)
    by_zone = {}
    node_zone = {n.name: n.metadata.labels["zone"] for n in nodes}
    placed = 0
    for key, node in got.items():
        if node is None:
            continue
        placed += 1
        by_zone[node_zone[node]] = by_zone.get(node_zone[node], 0) + 1
    assert placed == 96, placed
    counts = [by_zone.get(f"zone-{z}", 0) for z in range(4)]
    assert max(counts) - min(counts) <= 1, counts
