"""Differential tests for the batched device preemption planner.

The device plan (ops/preempt_solve.py, one jitted victim-selection dispatch)
must match the host planner (core/preemption.plan_preemptions — the oracle)
victim-for-victim and in the same order on randomized clusters: plain,
gang-flavored, and quota-held traces. Plus: the ordered-subset start_index
contract holds for every device plan, and the incremental victim-table
uploads are idempotent (incremental sync == cold rebuild, bit-identical; a
clean sync uploads nothing).
"""
import random

import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import ObjectMeta, PriorityClass, make_node, make_pod
from yunikorn_tpu.common.resource import ResourceBuilder, get_pod_resource
from yunikorn_tpu.common.si import (
    AllocationAsk,
    PreemptionPredicatesArgs,
    TerminationType,
)
from yunikorn_tpu.core.preemption import (
    plan_preemptions,
    plan_preemptions_batched,
)
from yunikorn_tpu.ops.preempt import preemption_victim_search
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder


def build_cluster(seed: int, n_nodes: int = 12, gang: bool = False):
    """Randomized cluster: nodes with bound victim pods at mixed priorities
    and sizes (exact in device units), a managed-app map, and an encoder
    synced to it."""
    rng = random.Random(seed)
    cache = SchedulerCache()
    app_of_pod = {}
    for i in range(n_nodes):
        cache.update_node(make_node(
            f"n{i:03d}", cpu_milli=4000, memory=8 * 2**30,
            labels={"zone": f"z{i % 3}"}))
        for j in range(rng.randint(0, 6)):
            kwargs = {}
            if gang and rng.random() < 0.5:
                kwargs = {"labels": {"placeholder": "true"}}
            v = make_pod(f"v-{i}-{j}", cpu_milli=rng.choice([250, 500, 1000, 1500]),
                         memory=rng.choice([2**28, 2**29]), node_name=f"n{i:03d}",
                         phase="Running", priority=rng.choice([0, 1, 1, 2, 5]),
                         **kwargs)
            # deterministic, distinct timestamps: the (priority asc, newest
            # first) ordering must not depend on construction wall time
            v.metadata.creation_timestamp = 1000.0 + rng.random() * 100
            cache.update_pod(v)
            app_of_pod[v.uid] = f"victim-app-{i % 4}"
    asks = []
    for k in range(rng.randint(2, 8)):
        p = make_pod(f"hi-{seed}-{k}",
                     cpu_milli=rng.choice([1000, 2000, 3000]),
                     memory=2**28,
                     priority=rng.choice([10, 50, 100]))
        if gang and k % 2 == 0:
            tg = "workers"
        else:
            tg = ""
        cache.update_pod(p)
        asks.append(AllocationAsk(p.uid, f"hi-app-{k % 2}",
                                  get_pod_resource(p), priority=p.spec.priority,
                                  pod=p, task_group_name=tg))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return cache, enc, asks, app_of_pod


def plans_key(plans):
    return [(p.ask.allocation_key, p.node_id, [v.uid for v in p.victims])
            for p in plans]


def both_planners(cache, enc, asks, app_of_pod, inflight=None):
    cands = list(cache.node_names())
    host, att_h = plan_preemptions(cache, asks, app_of_pod,
                                   inflight_by_node=inflight,
                                   candidate_nodes=cands)
    dev, att_d, stats = plan_preemptions_batched(
        cache, enc, asks, app_of_pod, inflight_by_node=inflight,
        candidate_nodes=cands)
    return host, dev, att_h, att_d, stats


# ---------------------------------------------------------------- plain trace

@pytest.mark.parametrize("seed", range(8))
def test_differential_plain_random(seed):
    cache, enc, asks, app_of_pod = build_cluster(seed)
    host, dev, att_h, att_d, stats = both_planners(cache, enc, asks, app_of_pod)
    assert plans_key(host) == plans_key(dev), (seed, stats)
    assert att_h == att_d
    assert stats["fallbacks"] == 0


@pytest.mark.parametrize("seed", (3, 7))
def test_differential_with_inflight_overlay(seed):
    """Capacity committed this cycle (inflight overlay) must gate both
    planners identically — victims are never evicted for capacity the
    cycle's own allocations will consume."""
    cache, enc, asks, app_of_pod = build_cluster(seed)
    names = cache.node_names()
    inflight = {names[0]: ResourceBuilder().cpu(3000).build(),
                names[1]: ResourceBuilder().cpu(1000).build()}
    host, dev, att_h, att_d, stats = both_planners(cache, enc, asks,
                                                   app_of_pod, inflight)
    assert plans_key(host) == plans_key(dev), (seed, stats)
    assert att_h == att_d


# ----------------------------------------------------------------- gang trace

@pytest.mark.parametrize("seed", range(4))
def test_differential_gang(seed):
    """Gang-flavored: placeholder-labelled victims, task-grouped asks."""
    cache, enc, asks, app_of_pod = build_cluster(seed + 100, gang=True)
    host, dev, att_h, att_d, stats = both_planners(cache, enc, asks, app_of_pod)
    assert plans_key(host) == plans_key(dev), (seed, stats)
    assert att_h == att_d


# ------------------------------------------------------------ protected pods

def test_differential_allow_preemption_optout():
    """PriorityClass opt-out filters the same victims from both tables."""
    cache, enc, asks, app_of_pod = build_cluster(42)
    pc = PriorityClass(metadata=ObjectMeta(
        name="protected",
        annotations={constants.ANNOTATION_ALLOW_PREEMPTION: "false"}))
    cache.update_priority_class(pc)
    protected = 0
    for uid in sorted(app_of_pod):
        if protected >= 5:
            break
        v = cache.get_pod(uid)
        if v is not None:
            v.spec.priority_class_name = "protected"
            cache.update_pod(v)
            protected += 1
    enc.sync_nodes()
    host, dev, att_h, att_d, stats = both_planners(cache, enc, asks, app_of_pod)
    assert plans_key(host) == plans_key(dev)
    chosen = {u for _, _, us in plans_key(dev) for u in us}
    for uid in chosen:
        assert cache.get_pod(uid).spec.priority_class_name != "protected"


# -------------------------------------------------- host-constrained asks

def test_constrained_asks_take_host_fallback_and_still_match():
    """Asks the device cannot model (host ports here) are re-planned on the
    host at finish; the combined result still matches the pure-host oracle
    when every ask is host-bound."""
    cache = SchedulerCache()
    cache.update_node(make_node("hn0", cpu_milli=4000, memory=8 * 2**30))
    app_of_pod = {}
    for j in range(3):
        v = make_pod(f"pv-{j}", cpu_milli=1500, node_name="hn0",
                     phase="Running", priority=0)
        v.metadata.creation_timestamp = 1000.0 + j
        cache.update_pod(v)
        app_of_pod[v.uid] = "victim-app"
    p = make_pod("hi-ported", cpu_milli=2000, priority=100)
    p.spec.containers[0].ports = [{"hostPort": 8080, "protocol": "TCP"}]
    cache.update_pod(p)
    ask = AllocationAsk(p.uid, "hi-app", get_pod_resource(p), priority=100,
                        pod=p)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    host, dev, att_h, att_d, stats = both_planners(cache, enc, [ask],
                                                   app_of_pod)
    assert stats["device_asks"] == 0        # the group is host-only
    assert plans_key(host) == plans_key(dev)
    assert len(dev) == 1 and dev[0].node_id == "hn0"


# ------------------------------------------------------------- quota-held

def make_core(cache, preempt_device):
    from yunikorn_tpu.core.scheduler import CoreScheduler, SolverOptions
    from yunikorn_tpu.common.si import RegisterResourceManagerRequest

    released = []

    class Callback:
        def update_allocation(self, response):
            for rel in response.released:
                if rel.termination_type == TerminationType.PREEMPTED_BY_SCHEDULER:
                    released.append(rel.allocation_key)

        def update_application(self, response):
            pass

        def update_node(self, response):
            pass

        def send_event(self, events):
            pass

        def update_container_scheduling_state(self, request):
            pass

    config = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: qv
          - name: qhi
            resources:
              max: {vcore: 3}
"""
    core = CoreScheduler(cache, solver_options=SolverOptions(
        preempt_device=preempt_device, pipeline=False))
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="t", policy_group="queues",
                                       config=config), Callback())
    return core, released


def run_quota_held_trace(preempt_device: bool):
    """Full-core trace: victims restored as existing allocations, a wave of
    high-priority asks partially held by queue quota; the unheld leftovers
    preempt. Returns the PREEMPTED_BY_SCHEDULER release keys in emit order."""
    from yunikorn_tpu.common.si import (
        AddApplicationRequest,
        Allocation,
        AllocationRequest,
        ApplicationRequest,
        NodeAction,
        NodeInfo,
        NodeRequest,
        UserGroupInfo,
    )

    cache = SchedulerCache()
    victims = []
    for i in range(4):
        cache.update_node(make_node(f"qn{i}", cpu_milli=2000, memory=8 * 2**30))
        v = make_pod(f"qv-{i}", cpu_milli=2000, memory=2**28,
                     node_name=f"qn{i}", phase="Running", priority=0)
        v.metadata.creation_timestamp = 1000.0 + i
        cache.update_pod(v)
        victims.append(v)
    core, released = make_core(cache, preempt_device)
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="victim-app", queue_name="root.qv",
                              user=UserGroupInfo(user="v")),
        AddApplicationRequest(application_id="hi-app", queue_name="root.qhi",
                              user=UserGroupInfo(user="h")),
    ]))
    infos = []
    for i, v in enumerate(victims):
        infos.append(NodeInfo(
            node_id=f"qn{i}", action=NodeAction.CREATE,
            existing_allocations=[Allocation(
                allocation_key=v.uid, application_id="victim-app",
                node_id=f"qn{i}", resource=get_pod_resource(v))]))
    core.update_node(NodeRequest(nodes=infos))
    asks = []
    for k in range(6):   # quota (3 vcore) holds all but ~1 of these 2-vcore asks
        p = make_pod(f"qhi-{k}", cpu_milli=2000, memory=2**28, priority=100)
        p.metadata.creation_timestamp = 2000.0 + k
        cache.update_pod(p)
        asks.append(AllocationAsk(p.uid, "hi-app", get_pod_resource(p),
                                  priority=100, pod=p))
    core.update_allocation(AllocationRequest(asks=asks))
    core.schedule_once()
    held = core.obs.get("unschedulable_total").value(reason="quota_held")
    return released, held


def test_differential_quota_held_trace():
    """Device-planned and host-planned cores must evict the same victims in
    the same order on a quota-held trace (some asks gated, the admitted
    leftover preempting)."""
    rel_host, held_host = run_quota_held_trace(preempt_device=False)
    rel_dev, held_dev = run_quota_held_trace(preempt_device=True)
    assert held_host == held_dev and held_host > 0
    # uids carry a process-global counter; compare by stable pod name
    names = lambda rels: [k.rsplit("-", 1)[0] for k in rels]
    assert names(rel_host) == names(rel_dev)
    assert rel_host, "the trace must actually preempt"


# ------------------------------------------- residue host-planning params

def test_host_planner_honors_seeded_claims_and_budget():
    """The core's residue pass (asks the device handle never saw) host-plans
    with the device plans' victims pre-claimed and a reduced ask budget —
    seeded victims must never be claimed twice, and max_asks must cap the
    attempts."""
    cache = SchedulerCache()
    cache.update_node(make_node("rn0", cpu_milli=4000, memory=8 * 2**30))
    app_of_pod = {}
    vs = []
    for j in range(4):
        v = make_pod(f"rv-{j}", cpu_milli=1000, node_name="rn0",
                     phase="Running", priority=0)
        v.metadata.creation_timestamp = 1000.0 + j
        cache.update_pod(v)
        app_of_pod[v.uid] = "victim-app"
        vs.append(v)
    asks = []
    for k in range(3):
        p = make_pod(f"rhi-{k}", cpu_milli=1000, priority=100)
        cache.update_pod(p)
        asks.append(AllocationAsk(p.uid, "hi", get_pod_resource(p),
                                  priority=100, pod=p))
    # table order is (prio asc, newest first) = rv-3, rv-2, rv-1, rv-0;
    # pre-claim the two the device would have chosen first
    seeded = {vs[3].uid, vs[2].uid}
    plans, att = plan_preemptions(cache, asks, app_of_pod,
                                  already_victim=set(seeded), max_asks=2)
    assert len(att) == 2                  # budget, not the full 3 asks
    chosen = {v.uid for p in plans for v in p.victims}
    assert not (chosen & seeded)          # seeded claims respected


# ---------------------------------------------------------- sharded parity

def test_sharded_preempt_matches_single_device():
    """Node-dimension sharding over the virtual 8-device CPU mesh must not
    change a single victim choice (same algorithm, different layout)."""
    from yunikorn_tpu.parallel.mesh import make_mesh

    cache, enc, asks, app_of_pod = build_cluster(11)
    cands = list(cache.node_names())
    single, _, _ = plan_preemptions_batched(
        cache, enc, asks, app_of_pod, candidate_nodes=cands)
    sharded, _, stats = plan_preemptions_batched(
        cache, enc, asks, app_of_pod, candidate_nodes=cands,
        mesh=make_mesh())
    assert stats["sharded"] is True
    assert plans_key(single) == plans_key(sharded)
    assert single, "scenario must produce plans"


# --------------------------------------------------- start_index contract

def test_device_plans_honor_start_index_contract():
    """Every device plan is the minimal ordered prefix: the exact victim-
    subset search over the plan's victims succeeds at the LAST index."""
    for seed in range(4):
        cache, enc, asks, app_of_pod = build_cluster(seed + 200)
        _, dev, _, _, _ = both_planners(cache, enc, asks, app_of_pod)
        for p in dev:
            resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
                allocation_key=p.ask.pod.uid, node_id=p.node_id,
                preempt_allocation_keys=[v.uid for v in p.victims],
                start_index=0))
            assert resp.success and resp.index == len(p.victims) - 1


# ------------------------------------------- incremental upload idempotence

def test_incremental_victim_tables_match_cold_rebuild():
    """Pod churn + incremental sync must produce BIT-IDENTICAL victim
    tables to a cold rebuild on a fresh encoder, and a no-change sync must
    not mark the device mirror dirty."""
    cache, enc, asks, app_of_pod = build_cluster(7)
    pc = cache.get_priority_class
    enc.sync_nodes()   # drain the cache's construction-time dirty set
    enc.sync_victims(app_of_pod, pc)

    # churn: delete one victim, add two new ones on other nodes
    gone = sorted(app_of_pod)[0]
    pod = cache.get_pod(gone)
    cache.remove_pod(pod)
    del app_of_pod[gone]
    names = cache.node_names()
    for t, nn in enumerate((names[1], names[-1])):
        v = make_pod(f"late-{t}", cpu_milli=500, node_name=nn,
                     phase="Running", priority=1)
        v.metadata.creation_timestamp = 3000.0 + t
        cache.update_pod(v)
        app_of_pod[v.uid] = "victim-app-9"
    enc.sync_nodes()                       # consumes the cache dirty set
    synced = enc.sync_victims(app_of_pod, pc)
    assert 0 < synced < len(names)         # incremental, not a full rebuild

    cold = SnapshotEncoder(cache)
    cold.sync_nodes(full=True)
    cold.sync_victims(app_of_pod, pc)

    a, b = enc.nodes, cold.nodes
    for name in names:
        ia, ib = a.index_of(name), b.index_of(name)
        np.testing.assert_array_equal(a.victim_req[ia], b.victim_req[ib])
        np.testing.assert_array_equal(a.victim_prio[ia], b.victim_prio[ib])
        np.testing.assert_array_equal(a.victim_valid[ia], b.victim_valid[ib])
        assert a.victim_uids.get(ia, ()) == b.victim_uids.get(ib, ())

    # idempotence: a second sync with no churn re-encodes nothing and
    # leaves the device-mirror dirty flag clear
    a.take_victim_dirty()
    assert enc.sync_victims(app_of_pod, pc) == 0
    assert a.take_victim_dirty() is False

    # and the plans on the churned cluster still agree
    host, dev, att_h, att_d, _ = both_planners(cache, enc, asks, app_of_pod)
    assert plans_key(host) == plans_key(dev)
