"""Reflector chaos tests: the failure modes client-go's reflector is built
around (watch replay windows, severed streams, 410 Gone storms, backoff),
driven against the fake API server's real REST protocol. Reference behavior:
client-go reflector semantics cited in client/kube.py."""
import ssl
import time

import pytest

from tests.fake_apiserver import FakeAPIServer
from yunikorn_tpu.client.interfaces import InformerType, ResourceEventHandlers
from yunikorn_tpu.client.kube import KubeConfig, RealAPIProvider


@pytest.fixture
def api():
    server = FakeAPIServer()
    port = server.start()
    cfg = KubeConfig(f"http://127.0.0.1:{port}", ssl.create_default_context())
    yield server, cfg
    server.stop()


def _wait(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def _node_provider(cfg, seen):
    provider = RealAPIProvider(cfg)
    provider.add_event_handler(InformerType.NODE, ResourceEventHandlers(
        add_fn=lambda n: seen.append(("add", n.name)),
        update_fn=lambda old, n: seen.append(("upd", n.name)),
        delete_fn=lambda n: seen.append(("del", n.name))))
    return provider


def test_event_between_list_and_watch_replayed(api):
    """An event emitted after LIST but before the WATCH connects must be
    replayed from the server's rv-indexed buffer — the flake ADVICE.md r2
    called out. Emulated deterministically: connect a watch at the rv of an
    earlier LIST and verify intermediate events arrive."""
    server, cfg = api
    server.add_node_doc("n0")
    with server._lock:
        list_rv = server._rv  # what a LIST at this instant would return
    # events land between the LIST and the WATCH connect
    server.add_node_doc("n1")
    server.delete("nodes", "", "n0")

    import json
    import urllib.request

    url = (f"{cfg.server}/api/v1/nodes?watch=true&resourceVersion={list_rv}"
           f"&allowWatchBookmarks=true")
    events = []
    with urllib.request.urlopen(url, timeout=5) as resp:
        for line in resp:
            events.append(json.loads(line))
            if len(events) == 2:
                break
    kinds = [(e["type"], e["object"]["metadata"]["name"]) for e in events]
    assert kinds == [("ADDED", "n1"), ("DELETED", "n0")]


def test_watch_killed_midstream_resumes_without_loss(api):
    server, cfg = api
    server.add_node_doc("n0")
    seen = []
    provider = _node_provider(cfg, seen)
    provider.start()
    provider.wait_for_sync(timeout=10)
    assert _wait(lambda: ("add", "n0") in seen)

    # sever every live watch stream, then immediately add a node: the
    # reflector must reconnect from its resume rv and deliver it
    killed = server.kill_watches()
    assert killed >= 1
    server.add_node_doc("n1")
    assert _wait(lambda: ("add", "n1") in seen), seen
    provider.stop()


def test_410_storm_forces_relist_and_recovers(api):
    server, cfg = api
    server.add_node_doc("n0")
    seen = []
    provider = _node_provider(cfg, seen)
    provider.start()
    provider.wait_for_sync(timeout=10)
    assert _wait(lambda: ("add", "n0") in seen)

    for _ in range(3):
        # compact the event log so the reflector's resume rv is too old,
        # then kill the stream: reconnect gets ERROR 410 → relist
        server.compact("nodes")
        server.kill_watches("nodes")
        time.sleep(0.1)
    server.add_node_doc("n-after-storm")
    assert _wait(lambda: any(n == "n-after-storm" for _, n in seen)), seen
    # the relists must not have manufactured spurious deletes
    assert ("del", "n0") not in seen
    provider.stop()


def test_informer_error_backoff_is_exponential():
    """Server errors on every request: reconnect attempts must slow down
    (exponential backoff with jitter), not hammer at a fixed rate."""
    from yunikorn_tpu.client.kube import _Informer

    class FailingClient:
        def __init__(self):
            self.attempts = []

        def request_json(self, *a, **k):
            self.attempts.append(time.monotonic())
            raise ConnectionError("boom")

        def _request(self, *a, **k):  # pragma: no cover - relist fails first
            raise ConnectionError("boom")

    client = FailingClient()
    inf = _Informer(client, InformerType.NODE)
    inf._BACKOFF_BASE = 0.05
    inf.run()
    deadline = time.time() + 4
    while len(client.attempts) < 5 and time.time() < deadline:
        time.sleep(0.02)
    inf.stop()
    assert len(client.attempts) >= 5, "informer stopped retrying"
    gaps = [b - a for a, b in zip(client.attempts, client.attempts[1:])]
    # later gaps must be materially larger than the first (doubling, with
    # jitter in [0.5x, 1.5x]) — a fixed-interval retry loop fails this
    assert gaps[3] > gaps[0] * 1.9, gaps


def test_informer_backoff_caps_and_restarts_are_exported():
    """Under a PERMANENTLY failing server the reflector's exponential
    backoff must cap at _BACKOFF_MAX (recovery latency after a long outage
    stays bounded) and every restart must be counted in
    informer_restarts_total — not only warned into the log."""
    from yunikorn_tpu.client.kube import _Informer
    from yunikorn_tpu.obs.metrics import MetricsRegistry

    class FailingClient:
        def __init__(self):
            self.attempts = []

        def request_json(self, *a, **k):
            self.attempts.append(time.monotonic())
            raise ConnectionError("boom")

        def _request(self, *a, **k):  # pragma: no cover - relist fails first
            raise ConnectionError("boom")

    client = FailingClient()
    inf = _Informer(client, InformerType.NODE)
    inf._BACKOFF_BASE = 0.02
    inf._BACKOFF_MAX = 0.15
    reg = MetricsRegistry()
    inf.attach_metrics(reg)
    inf.run()
    deadline = time.time() + 8
    # enough attempts that the doubling (0.02 -> 0.15 cap) has saturated
    while len(client.attempts) < 10 and time.time() < deadline:
        time.sleep(0.02)
    inf.stop()
    attempts = list(client.attempts)
    assert len(attempts) >= 10, "informer stopped retrying"
    gaps = [b - a for a, b in zip(attempts, attempts[1:])]
    # capped: every gap stays under _BACKOFF_MAX * 1.5 (the jitter ceiling)
    # plus scheduling slack — unbounded doubling fails this
    assert max(gaps) < 0.15 * 1.5 + 0.2, gaps
    # ...but it really did back off from the base before capping
    assert max(gaps[3:]) > 0.02, gaps
    # every restart counted, with the informer label
    restarts = reg.get("informer_restarts_total")
    assert restarts is not None
    assert restarts.value(informer=InformerType.NODE.value) >= len(attempts) - 1
    assert inf.restarts >= len(attempts) - 1
    # never synced: the staleness probe reports None, not a bogus age
    assert inf.sync_age() is None


def test_informer_sync_age_tracks_progress(api):
    """A healthy informer's sync age resets on list/watch progress and is
    exported through the provider's sync_ages (the health monitor input)."""
    server, cfg = api
    server.add_node_doc("sa-n0")
    provider = RealAPIProvider(cfg)
    from yunikorn_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    provider.attach_metrics(reg)
    provider.start()
    provider.wait_for_sync(timeout=10)
    try:
        ages = provider.sync_ages()
        assert ages[InformerType.NODE.value] is not None
        assert ages[InformerType.NODE.value] < 30
        assert provider.restart_count() == 0
        # the gauge landed in the registry with the informer label
        g = reg.get("informer_last_sync_age_seconds")
        assert g is not None
        assert g.value(informer=InformerType.NODE.value) < 30
    finally:
        provider.stop()


def test_informer_sync_age_refreshes_at_scrape():
    """A wedged informer (synced once, then nothing) must show a GROWING
    last-sync age to a scrape-only deployment: the gauge refreshes at
    exposition time, not only when a health probe happens to call
    sync_age() — otherwise it reads a flat 0 during exactly the staleness
    incident it exists to surface."""
    from yunikorn_tpu.client.kube import _Informer
    from yunikorn_tpu.obs.metrics import MetricsRegistry

    inf = _Informer(object(), InformerType.NODE)
    reg = MetricsRegistry()
    inf.attach_metrics(reg)
    inf._note_sync()                  # synced once (timestamp only: the
    g = reg.get("informer_last_sync_age_seconds")  # gauge is scrape-derived)
    reg.expose()
    assert g.value(informer=InformerType.NODE.value) < 0.2
    time.sleep(0.25)                  # ...then the reflector wedges
    text = reg.expose()               # a Prometheus scrape, nothing else
    assert g.value(informer=InformerType.NODE.value) >= 0.2
    assert "informer_last_sync_age_seconds" in text
    # the JSON surface (/ws/v1/metrics renders the same registry) too
    time.sleep(0.1)
    snap = reg.snapshot()
    assert snap["informer_last_sync_age_seconds"][
        f"informer={InformerType.NODE.value}"] >= 0.3


def test_partial_sync_timeout_names_the_laggard(api):
    """wait_for_sync failing must say WHICH informer didn't sync."""
    server, cfg = api
    provider = RealAPIProvider(cfg)
    # do not start(): nothing syncs
    with pytest.raises(TimeoutError) as exc:
        provider.wait_for_sync(timeout=0.3)
    assert "informer" in str(exc.value)


def test_store_snapshot_consistent_under_churn(api):
    """list_pods during heavy watch churn must not raise (store lock)."""
    server, cfg = api
    provider = RealAPIProvider(cfg)
    provider.start()
    provider.wait_for_sync(timeout=10)

    import threading

    stop = threading.Event()
    errors = []

    def churn():
        i = 0
        while not stop.is_set():
            server.add_pod_doc(f"p{i % 50}", app_id="churn")
            if i % 7 == 0:
                server.delete("pods", "default", f"p{(i - 3) % 50}")
            i += 1

    def read():
        while not stop.is_set():
            try:
                provider.list_pods()
            except Exception as e:  # pragma: no cover - the bug under test
                errors.append(e)
                return

    threads = [threading.Thread(target=churn), threading.Thread(target=read),
               threading.Thread(target=read)]
    for t in threads:
        t.start()
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors
    provider.stop()


def test_transient_connection_errors_retried(api):
    """request_json retries connection-level failures with backoff; HTTP
    status errors pass through untouched."""
    import urllib.error

    from yunikorn_tpu.client.kube import KubeConfig, RealKubeClient

    server, cfg = api
    client = RealKubeClient(cfg)
    calls = {"n": 0}
    real = client._request

    def flaky(method, path, body=None, content_type="application/json",
              timeout=30.0):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise ConnectionResetError(104, "Connection reset by peer")
        return real(method, path, body, content_type, timeout)

    client._request = flaky
    server.add_node_doc("rt-n0")
    doc = client.request_json("GET", "/api/v1/nodes/rt-n0")
    assert doc["metadata"]["name"] == "rt-n0"
    assert calls["n"] == 3                      # two resets, one success

    before = calls["n"]                         # stub past its flaky window
    with pytest.raises(urllib.error.HTTPError):
        client.request_json("GET", "/api/v1/nodes/does-not-exist")
    assert calls["n"] == before + 1             # 404 not retried


def test_bind_retry_after_committed_first_attempt(api):
    """A bind whose first POST landed but whose response was lost (connection
    reset) is retried; the retry's 409 Conflict resolves to success because
    the pod is assigned to OUR node. A 409 against a different node raises."""
    import urllib.error

    from yunikorn_tpu.client.k8s_codec import decode_pod
    from yunikorn_tpu.client.kube import KubeConfig, RealKubeClient

    server, cfg = api
    client = RealKubeClient(cfg)
    server.add_node_doc("bn0")
    server.add_pod_doc("bp0")
    pod = decode_pod(server.store["pods"]["default/bp0"])

    # sever the response of the FIRST binding POST only
    real = client._request
    state = {"first": True}

    def reset_after_commit(method, path, body=None,
                           content_type="application/json", timeout=30.0):
        if path.endswith("/binding") and state["first"]:
            state["first"] = False
            real(method, path, body, content_type, timeout).read()  # commits
            raise ConnectionResetError(104, "Connection reset by peer")
        return real(method, path, body, content_type, timeout)

    client._request = reset_after_commit
    client.bind(pod, "bn0")                     # retry sees 409 -> ours -> ok
    assert server.bindings == [("bp0", "bn0")]  # exactly one binding

    # conflicting assignment to a DIFFERENT node must still raise
    server.add_pod_doc("bp1")
    pod1 = decode_pod(server.store["pods"]["default/bp1"])
    server.bind_pod("default", "bp1", "other-node")
    with pytest.raises(urllib.error.HTTPError):
        client.bind(pod1, "bn0")
