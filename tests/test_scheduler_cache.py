"""Tests for the external SchedulerCache + FakeCluster informers.

Mirrors the reference's scheduler_cache_test.go coverage: assign/unassign,
assume/forget, orphan adoption, PVC refcounts, terminated-pod cleanup.
"""
from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.client.fake import FakeCluster
from yunikorn_tpu.client.interfaces import InformerType, ResourceEventHandlers
from yunikorn_tpu.common.objects import Volume, make_node, make_pod


def test_add_node_and_assigned_pod():
    cache = SchedulerCache()
    cache.update_node(make_node("n1", cpu_milli=4000))
    pod = make_pod("p1", cpu_milli=1000, node_name="n1", phase="Running")
    assert cache.update_pod(pod) is True
    info = cache.get_node("n1")
    assert info.requested.get("cpu") == 1000
    assert info.available().get("cpu") == 3000
    assert cache.get_pod_node_name(pod.uid) == "n1"


def test_orphan_pod_adopted_when_node_appears():
    cache = SchedulerCache()
    pod = make_pod("p1", cpu_milli=500, node_name="ghost", phase="Running")
    assert cache.update_pod(pod) is False
    assert cache.is_pod_orphaned(pod.uid)
    adopted = cache.update_node(make_node("ghost"))
    assert [p.uid for p in adopted] == [pod.uid]
    assert not cache.is_pod_orphaned(pod.uid)
    assert cache.get_node("ghost").requested.get("cpu") == 500


def test_node_removal_orphans_pods():
    cache = SchedulerCache()
    cache.update_node(make_node("n1"))
    pod = make_pod("p1", cpu_milli=500, node_name="n1", phase="Running")
    cache.update_pod(pod)
    orphans = cache.remove_node("n1")
    assert [p.uid for p in orphans] == [pod.uid]
    assert cache.is_pod_orphaned(pod.uid)


def test_assume_and_forget():
    cache = SchedulerCache()
    cache.update_node(make_node("n1", cpu_milli=4000))
    pod = make_pod("p1", cpu_milli=1000)
    cache.update_pod(pod)
    pod.spec.node_name = "n1"
    cache.assume_pod(pod, all_volumes_bound=True)
    assert cache.is_assumed_pod(pod.uid)
    assert cache.are_pod_volumes_all_bound(pod.uid)
    assert cache.get_node("n1").requested.get("cpu") == 1000

    cache.forget_pod(pod)
    assert not cache.is_assumed_pod(pod.uid)
    assert cache.get_node("n1").requested.get("cpu") == 0
    assert pod.spec.node_name == ""


def test_running_update_clears_assumed():
    cache = SchedulerCache()
    cache.update_node(make_node("n1"))
    pod = make_pod("p1", cpu_milli=100)
    cache.update_pod(pod)
    pod.spec.node_name = "n1"
    cache.assume_pod(pod, all_volumes_bound=False)
    bound = pod.deepcopy()
    bound.status.phase = "Running"
    cache.update_pod(bound)
    assert not cache.is_assumed_pod(pod.uid)
    assert cache.get_node("n1").requested.get("cpu") == 100  # still assigned


def test_terminated_pod_fully_removed():
    cache = SchedulerCache()
    cache.update_node(make_node("n1"))
    pod = make_pod("p1", cpu_milli=100, node_name="n1", phase="Running")
    cache.update_pod(pod)
    done = pod.deepcopy()
    done.status.phase = "Succeeded"
    cache.update_pod(done)
    assert cache.get_pod(pod.uid) is None
    assert cache.get_node("n1").requested.get("cpu") == 0


def test_update_preserves_existing_assignment():
    cache = SchedulerCache()
    cache.update_node(make_node("n1"))
    pod = make_pod("p1", cpu_milli=100, node_name="n1", phase="Running")
    cache.update_pod(pod)
    newer = pod.deepcopy()
    newer.spec.node_name = ""  # update without nodeName keeps assignment
    cache.update_pod(newer)
    assert newer.spec.node_name == "n1"
    assert cache.get_pod_node_name(pod.uid) == "n1"


def test_pvc_ref_counts():
    cache = SchedulerCache()
    cache.update_node(make_node("n1"))
    pod = make_pod("p1", cpu_milli=100, node_name="n1", phase="Running")
    pod.spec.volumes = [Volume(name="v", pvc_claim_name="claim-a")]
    cache.update_pod(pod)
    assert cache.is_pvc_used_by_pods("default/claim-a")
    cache.remove_pod(pod)
    assert not cache.is_pvc_used_by_pods("default/claim-a")


def test_dirty_node_tracking():
    cache = SchedulerCache()
    cache.update_node(make_node("n1"))
    cache.update_node(make_node("n2"))
    cache.take_dirty_nodes()
    g0 = cache.generation()
    pod = make_pod("p1", cpu_milli=100, node_name="n2", phase="Running")
    cache.update_pod(pod)
    assert cache.generation() > g0
    dirty, objects = cache.take_dirty_nodes()
    assert dirty == {"n2"}
    assert objects == set()  # pod churn: free-only refresh suffices
    cache.update_node(make_node("n2"))
    dirty, objects = cache.take_dirty_nodes()
    assert objects == {"n2"}  # node object changed: full re-encode
    assert cache.take_dirty_nodes() == (set(), set())


# ---------------------------------------------------------------------------
# FakeCluster informer semantics
# ---------------------------------------------------------------------------

def test_fake_cluster_informer_fanout_and_replay():
    cluster = FakeCluster()
    seen = {"add": [], "update": [], "delete": []}
    cluster.add_node(make_node("n1"))  # before start: stored, no event yet
    cluster.add_event_handler(
        InformerType.NODE,
        ResourceEventHandlers(
            add_fn=lambda o: seen["add"].append(o.name),
            update_fn=lambda old, new: seen["update"].append(new.name),
            delete_fn=lambda o: seen["delete"].append(o.name),
        ),
    )
    cluster.start()  # replays existing objects
    assert seen["add"] == ["n1"]
    cluster.add_node(make_node("n2"))
    cluster.update_node(make_node("n1"))
    cluster.delete_node("n2")
    assert seen["add"] == ["n1", "n2"]
    assert seen["update"] == ["n1"]
    assert seen["delete"] == ["n2"]


def test_fake_cluster_bind_fires_update_and_stats():
    cluster = FakeCluster()
    cluster.start()
    cluster.add_node(make_node("n1"))
    pod = make_pod("p1", cpu_milli=100)
    cluster.add_pod(pod)
    updates = []
    cluster.add_event_handler(
        InformerType.POD,
        ResourceEventHandlers(update_fn=lambda old, new: updates.append((old.spec.node_name, new.spec.node_name))),
    )
    client = cluster.get_client()
    client.bind(pod, "n1")
    assert pod.spec.node_name == "n1"
    assert pod.status.phase == "Running"
    assert updates == [("", "n1")]
    assert client.bind_stats.success_count == 1
    assert client.bind_stats.throughput() > 0


def test_fake_cluster_filter_fn():
    cluster = FakeCluster()
    cluster.start()
    seen = []
    cluster.add_event_handler(
        InformerType.POD,
        ResourceEventHandlers(
            filter_fn=lambda p: p.namespace == "wanted",
            add_fn=lambda p: seen.append(p.name),
        ),
    )
    cluster.add_pod(make_pod("a", namespace="wanted"))
    cluster.add_pod(make_pod("b", namespace="other"))
    assert seen == ["a"]


def test_synthetic_generators():
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods

    nodes = make_kwok_nodes(5)
    assert len(nodes) == 5
    assert nodes[0].status.allocatable["pods"] == 110
    pods = make_sleep_pods(3, "app-1", queue="root.q1")
    assert len(pods) == 3
    assert pods[0].metadata.labels["applicationId"] == "app-1"
    assert pods[0].spec.scheduler_name == "yunikorn"
