"""Feasibility-parity property suite for the LP/ADMM pack solver
(ops/pack_solve.py, solver.policy=optimal).

The pack plan's contract: it may place a DIFFERENT set of pods than the
greedy solve — that is the point — but every placement it emits must pass
the exact greedy-side feasibility (host predicates, group screens, capacity
prefix-fit), the same seed must reproduce the same plan, a plan that does
not beat greedy must fall back, and a faulted pack path must leave the
cycle's placements exactly what the greedy policy would have committed.
"""
import random

import numpy as np
import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import (Affinity, NodeSelectorRequirement,
                                         NodeSelectorTerm, Taint, Toleration,
                                         make_node, make_pod)
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import (
    AddApplicationRequest,
    AllocationAsk,
    AllocationRequest,
    ApplicationRequest,
    NodeAction,
    NodeInfo,
    NodeRequest,
    RegisterResourceManagerRequest,
    UserGroupInfo,
)
from yunikorn_tpu.core.scheduler import CoreScheduler, SolverOptions
from yunikorn_tpu.ops import pack_solve as pack_mod
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.ops.host_predicates import pod_fits_node
from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

ZONES = ["z0", "z1", "z2"]
DISKS = ["ssd", "hdd"]


def random_node(rng, i):
    """Fragmented fleet: mixed capacities/flavors, some tainted/unschedulable."""
    flavor = rng.random()
    if flavor < 0.4:
        node = make_node(f"n{i:04d}", cpu_milli=8000, memory=4 * 2**30,
                         labels={"zone": rng.choice(ZONES),
                                 "disk": rng.choice(DISKS)})
    else:
        node = make_node(f"n{i:04d}", cpu_milli=rng.choice([2000, 4000]),
                         memory=rng.choice([8, 16]) * 2**30,
                         labels={"zone": rng.choice(ZONES),
                                 "disk": rng.choice(DISKS)})
    if rng.random() < 0.2:
        node.spec.taints = [Taint(key="dedicated", value="batch",
                                  effect="NoSchedule")]
    if rng.random() < 0.08:
        node.spec.unschedulable = True
    return node


def random_pod(rng, i):
    """Priority-skewed mixed sizes with a sprinkling of constraints."""
    if rng.random() < 0.5:
        pod = make_pod(f"p{i}", cpu_milli=rng.choice([1500, 1900]),
                       memory=2**28, priority=rng.choice([0, 1, 5]))
    else:
        pod = make_pod(f"p{i}", cpu_milli=rng.choice([200, 400]),
                       memory=rng.choice([1, 3]) * 2**30,
                       priority=rng.choice([0, 1, 5]))
    r = rng.random()
    if r < 0.2:
        pod.spec.node_selector = {"zone": rng.choice(ZONES)}
    elif r < 0.3:
        pod.spec.affinity = Affinity(node_required_terms=[NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement(
                "disk", rng.choice(["In", "NotIn"]), [rng.choice(DISKS)])])])
    if rng.random() < 0.15:
        pod.spec.tolerations = [Toleration(key="dedicated", operator="Equal",
                                           value="batch",
                                           effect="NoSchedule")]
    return pod


def build_trace(seed, n_nodes=48, n_pods=160):
    rng = random.Random(seed)
    cache = SchedulerCache()
    nodes = [random_node(rng, i) for i in range(n_nodes)]
    for n in nodes:
        cache.update_node(n)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [random_pod(rng, i) for i in range(n_pods)]
    asks = [AllocationAsk(p.uid, "pack-app", get_pod_resource(p), pod=p)
            for p in pods]
    return cache, enc, nodes, pods, asks, enc.build_batch(asks)


# ---------------------------------------------------------- feasibility parity
@pytest.mark.parametrize("seed", range(8))
def test_pack_placements_pass_greedy_side_feasibility(seed):
    """Every placement the pack plan emits must satisfy the exact host
    predicates and per-node capacity — i.e. nothing greedy-side feasibility
    would reject, on randomized fragmented/priority-skew traces."""
    cache, enc, nodes, pods, asks, batch = build_trace(seed)
    result = pack_mod.pack_solve_batch(batch, enc.nodes, seed=seed)
    assigned = np.asarray(result.assigned)[: batch.num_pods]
    assert int(np.asarray(result.free_after).min()) >= 0

    by_name = {n.name: n for n in nodes}
    placed_on = {}
    for i, pod in enumerate(pods):
        idx = int(assigned[i])
        if idx >= 0:
            placed_on.setdefault(enc.nodes.name_of(idx), []).append(pod)
    for name, placed in placed_on.items():
        node = by_name[name]
        free = cache.get_node(name).available()
        for k, pod in enumerate(placed):
            others = placed[:k] + placed[k + 1:]
            err = pod_fits_node(pod, node, free, others)
            assert err in (None, "insufficient resources"), (
                seed, name, pod.name, err)
        for res in ("cpu", "memory"):
            total = sum(get_pod_resource(p).get(res) for p in placed)
            assert total <= free.get(res), (seed, name, res, total)


@pytest.mark.parametrize("seed", range(4))
def test_pack_seeded_determinism(seed):
    """Same seed -> bit-identical plan; a different seed may repartition."""
    _, enc, _, _, _, batch = build_trace(seed)
    a = np.asarray(pack_mod.pack_solve_batch(batch, enc.nodes,
                                             seed=123).assigned)
    b = np.asarray(pack_mod.pack_solve_batch(batch, enc.nodes,
                                             seed=123).assigned)
    assert np.array_equal(a, b)


def test_pack_repair_places_strandable_pods():
    """Per-subproblem fallback: with abundant homogeneous capacity every
    valid pod must place — a random partition that strands pods in an
    exhausted part is repaired by the greedy pass over the full node set."""
    cache = SchedulerCache()
    for i in range(32):
        cache.update_node(make_node(f"n{i:03d}", cpu_milli=16000,
                                    memory=64 * 2**30))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"p{i}", cpu_milli=500, memory=2**28) for i in range(256)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p)
            for p in pods]
    batch = enc.build_batch(asks)
    result = pack_mod.pack_solve_batch(batch, enc.nodes, seed=1)
    assigned = np.asarray(result.assigned)[: batch.num_pods]
    assert int((assigned >= 0).sum()) == len(pods)


def test_choose_plan_falls_back_when_pack_not_better():
    """The differential decision rule: pack commits only on a strictly
    better (priority classes, placed, normalized units, -nodes) key; ties
    keep greedy."""
    req = np.full((4, 2), 10, np.int32)
    valid = np.ones(4, bool)
    g = np.array([0, 0, 1, -1], np.int32)
    same = np.array([1, 1, 0, -1], np.int32)
    fewer = np.array([0, 0, -1, -1], np.int32)
    more = np.array([0, 0, 1, 1], np.int32)
    denser = np.array([0, 0, 0, -1], np.int32)
    assert not pack_mod.choose_plan(g, same, req, valid)[0]     # tie → greedy
    assert not pack_mod.choose_plan(g, fewer, req, valid)[0]
    assert pack_mod.choose_plan(g, more, req, valid)[0]
    assert pack_mod.choose_plan(g, denser, req, valid)[0]       # fewer nodes


def test_choose_plan_priority_guard_blocks_starvation():
    """Priority Matters: a pack plan that packs MORE units by displacing a
    high-priority ask for bulkier low-priority ones must LOSE, class by
    class from the top; within a class, packing quality still decides."""
    # ask 0: priority 100, small; asks 1-3: priority 0, large
    req = np.array([[1, 1], [50, 50], [50, 50], [50, 50]], np.int32)
    valid = np.ones(4, bool)
    prio = np.array([100, 0, 0, 0], np.int64)
    greedy = np.array([0, 0, -1, -1], np.int32)   # places the prio-100 ask
    pack = np.array([-1, 0, 1, 2], np.int32)      # more units, starves it
    use, _ = pack_mod.choose_plan(greedy, pack, req, valid, priorities=prio)
    assert not use
    # without the guard the units win: the priorities arg IS the guard
    assert pack_mod.choose_plan(greedy, pack, req, valid)[0]
    # same top-class coverage + more low-priority placed → pack wins
    pack_ok = np.array([0, 0, 1, 2], np.int32)
    assert pack_mod.choose_plan(greedy, pack_ok, req, valid,
                                priorities=prio)[0]


def test_choose_plan_capacity_normalized_units():
    """The commit objective matches the solver's: per-column normalization
    by mean node capacity, so a bulky raw-integer column (bytes) cannot
    outvote the contended scored column (milliCPU)."""
    # col 0: capacity 10/node (scarce); col 1: capacity 1e6/node (bulky)
    cap = np.array([[10, 10**6]] * 4, np.int64)
    valid = np.ones(2, bool)
    # plan A places the scarce-column ask, plan B the bulky-column ask
    req = np.array([[10, 0], [0, 10**5]], np.int32)
    a = np.array([0, -1], np.int32)
    b = np.array([-1, 1], np.int32)
    # raw units would prefer B (1e5 > 10); normalized prefers A (1.0 > 0.1)
    use_b, st = pack_mod.choose_plan(a, b, req, valid, cap_i=cap)
    assert not use_b, st
    assert pack_mod.choose_plan(a, b, req, valid)[0]  # raw units: B wins


def test_pack_unsupported_batches_raise():
    """Locality and host-port batches are outside the model: explicit
    PackUnsupported, never a silently wrong plan."""
    cache = SchedulerCache()
    for i in range(4):
        cache.update_node(make_node(f"n{i}", cpu_milli=4000, memory=8 * 2**30,
                                    labels={"zone": "z0"}))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    port_pod = make_pod("pp", cpu_milli=100, memory=2**20)
    port_pod.spec.containers[0].ports = [{"hostPort": 9000, "protocol": "TCP"}]
    batch = enc.build_batch([AllocationAsk(
        port_pod.uid, "app", get_pod_resource(port_pod), pod=port_pod)])
    with pytest.raises(pack_mod.PackUnsupported):
        pack_mod.pack_solve_batch(batch, enc.nodes)

    from yunikorn_tpu.common.objects import TopologySpreadConstraint

    spread = make_pod("sp", cpu_milli=100, memory=2**20,
                      labels={"grp": "a"})
    spread.spec.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1, topology_key="zone",
        when_unsatisfiable="DoNotSchedule", label_selector={"grp": "a"})]
    batch2 = enc.build_batch([AllocationAsk(
        spread.uid, "app", get_pod_resource(spread), pod=spread)])
    if batch2.locality is not None:
        with pytest.raises(pack_mod.PackUnsupported):
            pack_mod.pack_solve_batch(batch2, enc.nodes)


def test_pack_beats_greedy_on_contended_shape():
    """The A/B the feature exists for: heterogeneous node flavors under a
    mixed cpu-heavy/mem-heavy wave — the pack plan must win the comparison."""
    cache = SchedulerCache()
    rng = random.Random(3)
    for i in range(128):
        if i % 2 == 0:
            cache.update_node(make_node(f"n{i:03d}", cpu_milli=8000,
                                        memory=4 * 2**30))
        else:
            cache.update_node(make_node(f"n{i:03d}", cpu_milli=2000,
                                        memory=16 * 2**30))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = []
    for i in range(1024):
        if rng.random() < 0.5:
            pods.append(make_pod(f"p{i}", cpu_milli=1900, memory=2**28,
                                 priority=rng.choice([0, 5])))
        else:
            pods.append(make_pod(f"p{i}", cpu_milli=300, memory=3 * 2**30,
                                 priority=rng.choice([0, 5])))
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p)
            for p in pods]
    batch = enc.build_batch(asks)
    ga = np.asarray(solve_batch(batch, enc.nodes).assigned)[: batch.num_pods]
    pa = np.asarray(pack_mod.pack_solve_batch(
        batch, enc.nodes, seed=7).assigned)[: batch.num_pods]
    use_pack, stats = pack_mod.choose_plan(ga, pa, batch.req.astype(np.int32),
                                           batch.valid)
    assert use_pack, stats
    assert stats["pack"]["units"] > stats["greedy"]["units"]


# ------------------------------------------------------------------ core e2e
class _CB:
    def update_allocation(self, r): pass
    def update_application(self, r): pass
    def update_node(self, r): pass
    def predicates(self, a): return None
    def preemption_predicates(self, a): return None
    def send_event(self, e): pass
    def update_container_scheduling_state(self, r): pass
    def get_state_dump(self): return "{}"


def make_core(policy="optimal", queues_yaml=None):
    cache = SchedulerCache()
    core = CoreScheduler(cache, solver_options=SolverOptions(policy=policy))
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="t", policy_group="queues",
                                       config=queues_yaml or ""),
        _CB())
    return cache, core


def run_core_trace(core, cache, n_nodes=32, waves=2, per_wave=60,
                   gang=False, cpu=400):
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods

    nodes = make_kwok_nodes(n_nodes)
    infos = []
    for n in nodes:
        cache.update_node(n)
        infos.append(NodeInfo(node_id=n.name, action=NodeAction.CREATE))
    core.update_node(NodeRequest(nodes=infos))
    core.update_application(ApplicationRequest(new=[AddApplicationRequest(
        application_id="app", queue_name="root.q",
        user=UserGroupInfo(user="u"))]))
    placements = {}
    names = {}
    for w in range(waves):
        pods = make_sleep_pods(per_wave, "app", queue="root.q",
                               name_prefix=f"w{w}", cpu_milli=cpu)
        asks = []
        for p in pods:
            names[p.uid] = p.metadata.name
            ask = AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p)
            if gang:
                ask.task_group_name = f"tg{w}"
            asks.append(ask)
        core.update_allocation(AllocationRequest(asks=asks))
        core.schedule_once()
        app = core.partition.applications.get("app")
        for key, alloc in app.allocations.items():
            placements[names.get(key, key)] = alloc.node_id
    return placements


@pytest.mark.parametrize("gang", [False, True])
def test_core_optimal_policy_commits_valid_plan(gang):
    """solver.policy=optimal through the full core cycle (incl. gang-tagged
    asks): every committed allocation lands on a real node within capacity,
    and the cycle entry carries the policy A/B keys."""
    cache, core = make_core("optimal")
    placements = run_core_trace(core, cache, gang=gang)
    assert len(placements) == 120
    per_node = {}
    for key, node in placements.items():
        per_node[node] = per_node.get(node, 0) + 400
    for node, used in per_node.items():
        info = cache.get_node(node)
        assert info is not None
        assert used <= info.allocatable.get("cpu")
    entry = (core.metrics.get("last_cycle") or {}).get("default") or {}
    assert entry.get("solver_policy") in ("greedy", "optimal")
    assert "pack_plan_ms" in entry or "pack_skip" in entry


def test_core_quota_held_trace_matches_greedy_admission():
    """Quota-held traces: the optimal policy must never place more than the
    quota admits — the gate runs before either solver and is policy-blind."""
    queues_yaml = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: q
            resources:
              max: {vcore: 10}
"""
    cache_g, core_g = make_core("greedy", queues_yaml)
    got_g = run_core_trace(core_g, cache_g, waves=1, per_wave=60)
    cache_o, core_o = make_core("optimal", queues_yaml)
    got_o = run_core_trace(core_o, cache_o, waves=1, per_wave=60)
    # quota admits 25 pods of 400m; both policies must commit exactly those
    assert len(got_g) == len(got_o) == 25


def test_core_pack_fault_falls_back_to_greedy_placements():
    """A faulted pack path must leave the cycle exactly greedy: placements
    identical to a policy=greedy run, outcome counted, loop never wedged."""
    cache_g, core_g = make_core("greedy")
    want = run_core_trace(core_g, cache_g)

    cache_o, core_o = make_core("optimal")
    core_o.supervisor.faults.fail("pack", times=8, tier="device")
    got = run_core_trace(core_o, cache_o)
    assert got == want
    c = core_o.obs.get("pack_plans_total")
    assert c.value(outcome="failed") + c.value(outcome="skipped") >= 1


def test_conf_policy_parsing_and_rejection():
    """solver.policy parses through the validated choice helper; unknown
    values for any enumerated option reject the update loudly."""
    from yunikorn_tpu.conf import schedulerconf as sc

    conf = sc.parse_config_map({"solver.policy": "optimal"})
    assert conf.solver_policy == "optimal"
    assert SolverOptions.from_conf(conf).policy == "optimal"
    conf = sc.parse_config_map({"solver.policy": "auto"})
    assert SolverOptions.from_conf(conf).policy == "greedy"
    for key, bad in (("solver.policy", "fastest"),
                     ("solver.gateVectorized", "maybe"),
                     ("solver.gateDevice", "1"),
                     ("solver.preemptDevice", "yes"),
                     ("solver.gateVerify", "auto")):
        with pytest.raises(ValueError):
            sc.parse_config_map({key: bad})
    # the holder rejects a hot-reload update and keeps serving the old
    # config; an invalid INITIAL configmap fails the boot loudly (there is
    # no previous config — swallowing it would run everything on defaults)
    holder = sc.ConfHolder()
    holder.update_config_maps([{"solver.policy": "optimal"}], initial=True)
    kept = holder.update_config_maps([{"solver.policy": "bogus"}])
    assert kept.solver_policy == "optimal"
    with pytest.raises(ValueError):
        sc.ConfHolder().update_config_maps([{"solver.policy": "bogus"}],
                                           initial=True)


def test_pack_with_device_mirror_and_node_mask():
    """The pack dispatch reuses the greedy dispatch's persistent device
    mirror; with a partition node mask the masked nodes must stay excluded
    (regression: the device-state + node_mask path had an undefined-name
    bug that silently disabled the mirror for every masked solve)."""
    cache = SchedulerCache()
    for i in range(16):
        cache.update_node(make_node(f"n{i:02d}", cpu_milli=4000,
                                    memory=8 * 2**30))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"p{i}", cpu_milli=500, memory=2**20)
            for i in range(64)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p)
            for p in pods]
    batch = enc.build_batch(asks)
    mask = np.zeros(enc.nodes.capacity, bool)
    allowed = {enc.nodes.index_of(f"n{i:02d}") for i in range(8)}
    for idx in allowed:
        mask[idx] = True
    dev = enc.device_arrays()
    result = pack_mod.pack_solve_batch(batch, enc.nodes, node_mask=mask,
                                       device_state=dev, seed=1)
    assigned = np.asarray(result.assigned)[: batch.num_pods]
    assert (assigned >= 0).all()
    assert set(assigned.tolist()) <= allowed
