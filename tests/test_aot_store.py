"""AOT executable store (aot/): fingerprint invalidation, corrupt-entry
quarantine, store-hit dispatch, and the offline-builder/runtime contract.

The cross-process half (a FRESH process serving its first cycle from the
store with zero compiles, placement-identical to a cold-compiled run) lives
in scripts/aot_smoke.py (`make aot-smoke`); these tests pin the in-process
invariants: any fingerprint component changing must MISS the store, a
corrupt/truncated artifact must quarantine and fall through to a compile
(never crash), and a hit must execute without any trace+compile.
"""
from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from yunikorn_tpu import aot
from yunikorn_tpu.aot.runtime import AotRuntime
from yunikorn_tpu.aot.store import AotStore


@pytest.fixture(autouse=True)
def _no_global_runtime():
    """Every test starts and ends with AOT disabled; tests that install a
    runtime do so explicitly and this teardown always clears it."""
    prev = aot.set_runtime(None)
    yield
    rt = aot.get_runtime()
    if rt is not None:
        rt.flush(timeout=30.0)
    aot.set_runtime(prev)


@functools.partial(jax.jit, static_argnames=("k",))
def _toy(x, pair, opt=None, *, k=2):
    a, b = pair
    out = x * a + b * k
    if opt is not None:
        out = out + opt
    return out, out.sum()


def _toy_args(n=16, dtype=jnp.float32):
    x = jnp.ones((n,), dtype)
    return (x, (jnp.asarray(2, dtype), jnp.ones((n,), dtype)), None)


# ---------------------------------------------------------------- store I/O

def test_store_put_get_roundtrip(tmp_path):
    store = AotStore(str(tmp_path))
    manifest = {"path": "p", "x": 1}
    ok = store.put("p", "k1", manifest, b"payload-bytes", ("it",), ("ot",))
    assert ok
    rec = store.get("p", "k1")
    assert rec is not None
    m2, payload, it, ot = rec
    assert m2 == manifest and payload == b"payload-bytes"
    assert it == ("it",) and ot == ("ot",)
    assert store.entry_count() == 1
    assert store.get("p", "unknown-key") is None


def test_corrupt_entry_quarantined_and_missed(tmp_path):
    store = AotStore(str(tmp_path))
    store.put("p", "k1", {"m": 1}, b"data", None, None)
    fp = store._entry_path("p", "k1")
    # truncate: valid magic, mangled body — the digest check must catch it
    blob = open(fp, "rb").read()
    with open(fp, "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert store.get("p", "k1") is None
    assert store.corrupt_quarantined == 1
    assert store.entry_count() == 0
    assert len(os.listdir(store.quarantine_dir)) == 1
    # a second lookup is a plain miss, not another quarantine
    assert store.get("p", "k1") is None
    assert store.corrupt_quarantined == 1


def test_bad_magic_quarantined(tmp_path):
    store = AotStore(str(tmp_path))
    store.put("p", "k2", {}, b"x", None, None)
    fp = store._entry_path("p", "k2")
    with open(fp, "wb") as f:
        f.write(b"NOT-AN-AOT-ENTRY")
    assert store.get("p", "k2") is None
    assert store.corrupt_quarantined == 1


def test_lru_size_cap_evicts_oldest(tmp_path):
    store = AotStore(str(tmp_path), max_bytes=1 << 20)
    payload = b"z" * 1500
    for i in range(4):
        store.put("p", f"k{i}", {"i": i}, payload, None, None)
        now = time.time() + i  # strictly increasing mtimes
        os.utime(store._entry_path("p", f"k{i}"), (now, now))
    store.max_bytes = 4096  # shrink the cap, then enforce
    store._enforce_cap()
    assert store.entry_count() < 4
    assert store.evicted >= 1
    # the newest entry survives
    assert store.get("p", "k3") is not None


def test_persistent_cache_mirror_roundtrip(tmp_path):
    src = tmp_path / "live_cache"
    src.mkdir()
    (src / "entry-a").write_bytes(b"aaa")
    (src / "entry-b").write_bytes(b"bbb")
    store = AotStore(str(tmp_path / "store"))
    assert store.save_persistent_cache(str(src)) == 2
    # restore into an empty "fresh host" cache dir
    dst = tmp_path / "fresh_cache"
    assert store.restore_persistent_cache(str(dst)) == 2
    assert sorted(os.listdir(dst)) == ["entry-a", "entry-b"]
    # idempotent: nothing new to copy either way
    assert store.save_persistent_cache(str(src)) == 0
    assert store.restore_persistent_cache(str(dst)) == 0


# --------------------------------------------------- runtime hit/miss logic

def test_runtime_compiles_saves_then_fresh_runtime_hits(tmp_path):
    store = AotStore(str(tmp_path))
    rt1 = AotRuntime(store)
    aot.set_runtime(rt1)
    args = _toy_args()
    out1, s1 = aot.aot_call("toy", _toy, args, {"k": 3})
    assert rt1.stats()["misses"] == 1 and rt1.stats()["compiles"] == 1
    rt1.flush(timeout=30.0)
    assert store.entry_count() == 1

    # a "fresh process": new runtime, empty memory cache, same store
    rt2 = AotRuntime(store)
    aot.set_runtime(rt2)
    out2, s2 = aot.aot_call("toy", _toy, args, {"k": 3})
    st = rt2.stats()
    assert st["hits"] == 1 and st["compiles"] == 0 and st["loads"] == 1
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert float(s1) == float(s2)
    # repeat call: in-memory hit, no second load
    aot.aot_call("toy", _toy, args, {"k": 3})
    assert rt2.stats()["hits"] == 2 and rt2.stats()["loads"] == 1


def test_fingerprint_invalidation_components(tmp_path):
    """Each fingerprint component must produce a distinct key: bucket shape,
    dtype mode, static kwarg, jax/jaxlib version, backend topology, and the
    caller extra (mesh tag)."""
    store = AotStore(str(tmp_path))
    rt = AotRuntime(store)
    base = rt._key(rt.manifest("p", _toy_args(16), {"k": 2}))

    variants = {
        "shape": rt._key(rt.manifest("p", _toy_args(32), {"k": 2})),
        "dtype": rt._key(rt.manifest(
            "p", _toy_args(16, jnp.int32), {"k": 2})),
        "static": rt._key(rt.manifest("p", _toy_args(16), {"k": 5})),
        "extra": rt._key(rt.manifest("p", _toy_args(16), {"k": 2},
                                     extra=("mesh", 8))),
        "path": rt._key(rt.manifest("q", _toy_args(16), {"k": 2})),
    }
    rt_ver = AotRuntime(store, versions=("0.0.0-fake", "0.0.0-fake"))
    variants["jaxlib"] = rt_ver._key(
        rt_ver.manifest("p", _toy_args(16), {"k": 2}))
    rt_topo = AotRuntime(store, backend=("tpu", 4))
    variants["topology"] = rt_topo._key(
        rt_topo.manifest("p", _toy_args(16), {"k": 2}))

    for name, key in variants.items():
        assert key != base, f"{name} change did not invalidate the key"
    assert len(set(variants.values())) == len(variants)

    # identical inputs reproduce the key (stable across runtimes)
    rt_b = AotRuntime(store)
    assert rt_b._key(rt_b.manifest("p", _toy_args(16), {"k": 2})) == base


def test_x64_mode_in_fingerprint(tmp_path):
    from jax.experimental import enable_x64

    rt = AotRuntime(AotStore(str(tmp_path)))
    args = (np.ones((8,), np.int64),)
    k_plain = rt._key(rt.manifest("p", args, {}))
    with enable_x64():
        k_x64 = rt._key(rt.manifest("p", args, {}))
    assert k_plain != k_x64


def test_scalar_leaves_key_on_type_not_value(tmp_path):
    """A traced scalar's VALUE must not mint new entries (the pack seed)."""
    rt = AotRuntime(AotStore(str(tmp_path)))
    k1 = rt._key(rt.manifest("p", (jnp.ones((4,)), 7), {}))
    k2 = rt._key(rt.manifest("p", (jnp.ones((4,)), 12345), {}))
    k3 = rt._key(rt.manifest("p", (jnp.ones((4,)), 1.5), {}))
    assert k1 == k2
    assert k1 != k3  # int vs float scalar changes the traced program


def test_corrupt_artifact_falls_through_to_compile(tmp_path):
    store = AotStore(str(tmp_path))
    rt1 = AotRuntime(store)
    aot.set_runtime(rt1)
    args = _toy_args()
    aot.aot_call("toy", _toy, args, {"k": 3})
    rt1.flush(timeout=30.0)
    assert store.entry_count() == 1
    # bit-rot the artifact on disk
    name = [n for n in os.listdir(store.entries_dir)
            if n.endswith(".aotx")][0]
    fp = os.path.join(store.entries_dir, name)
    blob = bytearray(open(fp, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(fp, "wb") as f:
        f.write(bytes(blob))

    rt2 = AotRuntime(store)
    aot.set_runtime(rt2)
    out, s = aot.aot_call("toy", _toy, args, {"k": 3})  # must not raise
    st = rt2.stats()
    assert st["hits"] == 0 and st["misses"] == 1 and st["compiles"] == 1
    assert store.corrupt_quarantined == 1
    expected, _ = _toy(*args, k=3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(expected))


def test_bypass_context_skips_runtime(tmp_path):
    from yunikorn_tpu.aot import runtime as aot_rt

    rt = AotRuntime(AotStore(str(tmp_path)))
    aot.set_runtime(rt)
    with aot_rt.bypass():
        aot.aot_call("toy", _toy, _toy_args(), {"k": 3})
    assert rt.stats()["misses"] == 0 and rt.stats()["hits"] == 0


def test_no_runtime_is_passthrough():
    out, s = aot.aot_call("toy", _toy, _toy_args(), {"k": 3})
    expected, _ = _toy(*_toy_args(), k=3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(expected))


# --------------------------------------------- background compile (pending)

def test_background_mode_raises_pending_then_serves(tmp_path):
    store = AotStore(str(tmp_path))
    rt = AotRuntime(store, background_compile=True)
    aot.set_runtime(rt)
    args = _toy_args()
    with pytest.raises(aot.CompilePending):
        aot.aot_call("toy", _toy, args, {"k": 3}, pending_ok=True)
    # the compile thread lands the executable; later dispatches hit
    deadline = time.time() + 60
    while time.time() < deadline:
        if rt.stats()["pending"] == 0 and rt.stats()["compiles"] >= 1:
            break
        time.sleep(0.02)
    assert rt.stats()["compiles"] == 1
    out, _ = aot.aot_call("toy", _toy, args, {"k": 3}, pending_ok=True)
    assert rt.stats()["hits"] == 1
    expected, _ = _toy(*args, k=3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(expected))
    # pending_ok=False callers (the cpu tier, scripts) never see the raise
    aot.set_runtime(AotRuntime(AotStore(str(tmp_path / "s2")),
                               background_compile=True))
    out2, _ = aot.aot_call("toy", _toy, args, {"k": 3})
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(expected))


def test_background_compile_preserves_x64_mode(tmp_path):
    """A background compile spawned from inside enable_x64 (the gate scan)
    must lower under the same mode — otherwise the int64 avals canonicalize
    to int32 and a wrong-signature program lands under the fingerprint."""
    from jax.experimental import enable_x64

    f = jax.jit(lambda x: (x * 2).sum())
    rt = AotRuntime(AotStore(str(tmp_path)), background_compile=True)
    aot.set_runtime(rt)
    with enable_x64():
        args = (jnp.asarray(np.arange(8, dtype=np.int64)),)
        with pytest.raises(aot.CompilePending):
            aot.aot_call("x64prog", f, args, {}, pending_ok=True)
    deadline = time.time() + 60
    while time.time() < deadline and rt.stats()["pending"]:
        time.sleep(0.02)
    assert rt.stats()["compiles"] == 1 and rt.stats()["failed"] == 0
    with enable_x64():
        out = aot.aot_call("x64prog", f, args, {}, pending_ok=True)
    assert rt.stats()["hits"] == 1
    assert int(out) == int(np.arange(8, dtype=np.int64).sum() * 2)


def test_code_version_in_fingerprint(tmp_path):
    """A changed solver-source hash must miss the store (a store built
    before a code change can never serve the old algorithm silently)."""
    store = AotStore(str(tmp_path))
    rt_a = AotRuntime(store, code_version="aaaa")
    rt_b = AotRuntime(store, code_version="bbbb")
    k_a = rt_a._key(rt_a.manifest("p", _toy_args(), {"k": 2}))
    k_b = rt_b._key(rt_b.manifest("p", _toy_args(), {"k": 2}))
    assert k_a != k_b
    # the real hash is stable within a process
    from yunikorn_tpu.aot.runtime import _code_version

    assert _code_version() == _code_version()


def test_pending_classified_persistent():
    from yunikorn_tpu.robustness.supervisor import PERSISTENT, classify_error

    assert classify_error(aot.CompilePending("x")) == PERSISTENT


# ------------------------------------------------------- solver-path wiring

def test_solver_options_static_fields_invalidate(tmp_path):
    """A changed SolverOptions-driven static (max_rounds, policy) must miss
    the store and recompile — through the real solve_batch wiring."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.ops.assign import solve_batch
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for node in make_kwok_nodes(16):
        cache.update_node(node)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = make_sleep_pods(32, "a", queue="root.a")
    batch = enc.build_batch([
        AllocationAsk(p.uid, "a", get_pod_resource(p), pod=p) for p in pods])

    rt = AotRuntime(AotStore(str(tmp_path)))
    aot.set_runtime(rt)
    r1 = solve_batch(batch, enc.nodes)
    r1.block_until_ready()
    assert rt.stats()["compiles"] == 1
    # same variant again: in-memory hit, no trace
    solve_batch(batch, enc.nodes).block_until_ready()
    assert rt.stats()["compiles"] == 1 and rt.stats()["hits"] == 1
    # changed statics miss
    solve_batch(batch, enc.nodes, max_rounds=8).block_until_ready()
    assert rt.stats()["compiles"] == 2
    solve_batch(batch, enc.nodes, policy="spread").block_until_ready()
    assert rt.stats()["compiles"] == 3


def test_compile_only_build_loads_from_store(tmp_path):
    """The prewarm/compile_only route must populate the store and, in a
    fresh runtime, LOAD instead of compiling (what --prewarm + --aot-store
    does at process start)."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.ops.assign import solve_batch
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for node in make_kwok_nodes(16):
        cache.update_node(node)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = make_sleep_pods(32, "a", queue="root.a")
    batch = enc.build_batch([
        AllocationAsk(p.uid, "a", get_pod_resource(p), pod=p) for p in pods])

    store = AotStore(str(tmp_path))
    rt1 = AotRuntime(store)
    aot.set_runtime(rt1)
    solve_batch(batch, enc.nodes, compile_only=True)
    assert rt1.stats()["compiles"] == 1
    rt1.flush(timeout=30.0)
    assert store.entry_count() == 1

    rt2 = AotRuntime(store)
    aot.set_runtime(rt2)
    solve_batch(batch, enc.nodes, compile_only=True)   # prewarm: pure load
    assert rt2.stats()["loads"] == 1 and rt2.stats()["compiles"] == 0
    r = solve_batch(batch, enc.nodes)                  # production dispatch
    r.block_until_ready()
    assert rt2.stats()["hits"] == 1 and rt2.stats()["compiles"] == 0


def test_jc_delta_accounting_sees_aot_compiles(tmp_path):
    """aot compiles bypass the jit wrappers (fn.lower().compile() never
    grows fn._cache_size()), so jit_cache_entries folds the runtime's
    per-path compile tally in — the core's solve_compile_total / compiled
    span accounting must not go dark for store-attached processes."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.ops import assign as assign_mod
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for node in make_kwok_nodes(16):
        cache.update_node(node)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = make_sleep_pods(32, "a", queue="root.a")
    batch = enc.build_batch([
        AllocationAsk(p.uid, "a", get_pod_resource(p), pod=p) for p in pods])

    aot.set_runtime(AotRuntime(AotStore(str(tmp_path))))
    jc0 = assign_mod.jit_cache_entries()
    assign_mod.solve_batch(batch, enc.nodes).block_until_ready()
    jc1 = assign_mod.jit_cache_entries()
    assert jc1 == jc0 + 1          # the aot compile is visible as a delta
    assign_mod.solve_batch(batch, enc.nodes).block_until_ready()
    assert assign_mod.jit_cache_entries() == jc1   # a hit is not


def test_refused_variant_latches_without_backend_wide_fallout(tmp_path, monkeypatch):
    """A variant failing to serialize permanently must latch ONLY that
    fingerprint: other variants of the same path (e.g. the non-pallas
    static combination) and other paths keep saving, the persistent cache
    stays off, and a TRANSIENT failure latches nothing."""
    import jax.experimental.serialize_executable as se

    store = AotStore(str(tmp_path))
    rt = AotRuntime(store)
    aot.set_runtime(rt)
    # a good save first (backend demonstrably serializes)
    aot.aot_call("good", _toy, _toy_args(), {"k": 3})
    rt.flush(timeout=30.0)
    assert store.entry_count() == 1 and rt._saves_ok == 1

    real = se.serialize

    def unimplemented(compiled):
        raise RuntimeError("UNIMPLEMENTED: no serialization for this kernel")

    monkeypatch.setattr(se, "serialize", unimplemented)
    aot.aot_call("mosaic", _toy, _toy_args(32), {"k": 3})
    rt.flush(timeout=30.0)
    assert len(rt._refused_keys) == 1         # that fingerprint, latched
    assert not rt._serialize_refused          # NOT a backend-wide refusal
    assert store.entry_count() == 1
    monkeypatch.setattr(se, "serialize", real)
    # a DIFFERENT variant of the refused path still serializes and saves
    aot.aot_call("mosaic", _toy, _toy_args(64), {"k": 3})
    rt.flush(timeout=30.0)
    assert store.entry_count() == 2
    # a transient failure (MemoryError class) latches nothing
    def oom(compiled):
        raise MemoryError("serialize ran out of memory")

    monkeypatch.setattr(se, "serialize", oom)
    aot.aot_call("big", _toy, _toy_args(128), {"k": 3})
    rt.flush(timeout=30.0)
    assert len(rt._refused_keys) == 1
    monkeypatch.setattr(se, "serialize", real)
    # other paths unaffected throughout
    aot.aot_call("good2", _toy, _toy_args(256), {"k": 3})
    rt.flush(timeout=30.0)
    assert store.entry_count() == 3


def test_metrics_attached(tmp_path):
    from yunikorn_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    rt = AotRuntime(AotStore(str(tmp_path)))
    rt.attach(registry=reg)
    aot.set_runtime(rt)
    aot.aot_call("toy", _toy, _toy_args(), {"k": 3})
    aot.aot_call("toy", _toy, _toy_args(), {"k": 3})
    text = reg.expose()
    assert "yunikorn_aot_store_misses_total" in text
    assert 'path="toy"' in text
    assert "yunikorn_aot_store_hits_total 1" in text
    assert "yunikorn_jit_compile_ms_bucket" in text
