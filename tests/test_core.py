"""Core scheduler tests: queue tree, quotas, DRF ordering, solve cycle,
placeholder replacement/timeout — against a recording callback (no shim).
"""
import time
from typing import List

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.common.resource import Resource, ResourceBuilder, get_pod_resource
from yunikorn_tpu.common.si import (
    AddApplicationRequest,
    Allocation,
    AllocationAsk,
    AllocationRelease,
    AllocationRequest,
    ApplicationRequest,
    NodeAction,
    NodeInfo,
    NodeRequest,
    RegisterResourceManagerRequest,
    RemoveApplicationRequest,
    ResourceManagerCallback,
    TerminationType,
    UserGroupInfo,
)
from yunikorn_tpu.core.queues import QueueTree, parse_queues_yaml
from yunikorn_tpu.core.scheduler import CoreScheduler

QUEUES_YAML = """
partitions:
  - name: default
    nodesortpolicy:
      type: binpacking
    queues:
      - name: root
        queues:
          - name: default
          - name: limited
            resources:
              max: {vcore: 2, memory: 4Gi}
          - name: parent
            resources:
              max: {vcore: 10}
            queues:
              - name: childa
              - name: childb
"""


class RecordingCallback(ResourceManagerCallback):
    def __init__(self):
        self.allocations: List = []
        self.releases: List = []
        self.rejected_asks: List = []
        self.accepted_apps: List = []
        self.rejected_apps: List = []
        self.updated_apps: List = []
        self.accepted_nodes: List = []
        self.container_updates: List = []
        self.events: List = []

    def update_allocation(self, response):
        self.allocations.extend(response.new)
        self.releases.extend(response.released)
        self.rejected_asks.extend(response.rejected)

    def update_application(self, response):
        self.accepted_apps.extend(a.application_id for a in response.accepted)
        self.rejected_apps.extend((a.application_id, a.reason) for a in response.rejected)
        self.updated_apps.extend(response.updated)

    def update_node(self, response):
        self.accepted_nodes.extend(n.node_id for n in response.accepted)

    def predicates(self, args):
        return None

    def preemption_predicates(self, args):
        raise NotImplementedError

    def send_event(self, events):
        self.events.extend(events)

    def update_container_scheduling_state(self, request):
        self.container_updates.append(request)

    def get_state_dump(self) -> str:
        return "{}"


def make_core(nodes=2, node_cpu=8000, queues_yaml=QUEUES_YAML):
    cache = SchedulerCache()
    cb = RecordingCallback()
    core = CoreScheduler(cache)
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="rm-1", policy_group="queues", config=queues_yaml), cb
    )
    node_infos = []
    for i in range(nodes):
        n = make_node(f"node-{i}", cpu_milli=node_cpu, memory=16 * 2**30)
        cache.update_node(n)
        node_infos.append(NodeInfo(node_id=n.name, action=NodeAction.CREATE,
                                   schedulable_resource=ResourceBuilder().cpu(node_cpu).build()))
    core.update_node(NodeRequest(nodes=node_infos))
    return cache, cb, core


def add_app(core, app_id, queue="root.default", **kw):
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id=app_id, queue_name=queue,
                              user=UserGroupInfo(user="u1"), **kw)
    ]))


def ask_of(app_id, key, cpu=1000, mem=2**30, priority=0, **kw):
    pod = make_pod(key, cpu_milli=cpu, memory=mem)
    return AllocationAsk(allocation_key=key, application_id=app_id,
                         resource=get_pod_resource(pod), priority=priority, pod=pod, **kw)


# ---------------------------------------------------------------------------
# Queue tree
# ---------------------------------------------------------------------------

def test_parse_queues_yaml():
    cfg = parse_queues_yaml(QUEUES_YAML)
    assert cfg.name == "root"
    names = [c.name for c in cfg.children]
    assert names == ["default", "limited", "parent"]
    limited = cfg.children[1]
    assert limited.max_resource.get("cpu") == 2000
    assert limited.max_resource.get("memory") == 4 * 2**30


def test_queue_tree_resolution_and_dynamic_creation():
    tree = QueueTree(parse_queues_yaml(QUEUES_YAML))
    q = tree.resolve("root.default")
    assert q.full_name == "root.default"
    # dynamic creation under root
    q2 = tree.resolve("root.newqueue")
    assert q2 is not None and q2.dynamic
    # submitting to a parent queue fails
    assert tree.resolve("root.parent") is None
    # child under configured parent
    assert tree.resolve("root.parent.childa").full_name == "root.parent.childa"


def test_queue_accounting_and_quota():
    tree = QueueTree(parse_queues_yaml(QUEUES_YAML))
    q = tree.resolve("root.limited")
    r = ResourceBuilder().cpu(1000).memory(2**30).build()
    assert q.fits_quota(r)
    q.add_allocated(r)
    assert tree.root.allocated.get("cpu") == 1000  # rolls up
    big = ResourceBuilder().cpu(1500).build()
    assert not q.fits_quota(big)  # 1000 + 1500 > 2000
    q.remove_allocated(r)
    assert q.fits_quota(big)


def test_parent_quota_constrains_children():
    tree = QueueTree(parse_queues_yaml(QUEUES_YAML))
    qa = tree.resolve("root.parent.childa")
    qb = tree.resolve("root.parent.childb")
    qa.add_allocated(ResourceBuilder().cpu(8000).build())
    assert not qb.fits_quota(ResourceBuilder().cpu(3000).build())  # parent max 10
    assert qb.fits_quota(ResourceBuilder().cpu(2000).build())


# ---------------------------------------------------------------------------
# Core scheduler protocol
# ---------------------------------------------------------------------------

def test_node_registration_and_accept():
    cache, cb, core = make_core(nodes=3)
    assert sorted(cb.accepted_nodes) == ["node-0", "node-1", "node-2"]
    assert core.partition.active_node_count() == 3


def test_app_accept_and_reject():
    cache, cb, core = make_core()
    add_app(core, "app-ok", "root.default")
    add_app(core, "app-bad", "root.parent")  # parent queue: reject
    assert "app-ok" in cb.accepted_apps
    assert cb.rejected_apps and cb.rejected_apps[0][0] == "app-bad"


def test_end_to_end_allocation_cycle():
    cache, cb, core = make_core(nodes=2, node_cpu=8000)
    add_app(core, "app-1")
    asks = [ask_of("app-1", f"pod-{i}", cpu=1000) for i in range(4)]
    core.update_allocation(AllocationRequest(asks=asks))
    n = core.schedule_once()
    assert n == 4
    assert len(cb.allocations) == 4
    nodes = {a.node_id for a in cb.allocations}
    assert nodes <= {"node-0", "node-1"}
    app = core.partition.get_application("app-1")
    assert app.state == "Running"
    assert not app.pending_asks
    # queue accounting rolled up
    leaf = core.queues.resolve("root.default", create=False)
    assert leaf.allocated.get("cpu") == 4000


def test_quota_holds_asks_back():
    cache, cb, core = make_core(nodes=2, node_cpu=16000)
    add_app(core, "app-1", "root.limited")  # max 2 vcore
    asks = [ask_of("app-1", f"pod-{i}", cpu=1000, mem=2**20) for i in range(5)]
    core.update_allocation(AllocationRequest(asks=asks))
    n = core.schedule_once()
    assert n == 2  # quota-capped
    leaf = core.queues.resolve("root.limited", create=False)
    assert leaf.allocated.get("cpu") == 2000
    # release one → next cycle admits one more
    rel = AllocationRelease(application_id="app-1",
                            allocation_key=cb.allocations[0].allocation_key,
                            termination_type=TerminationType.STOPPED_BY_RM)
    core.update_allocation(AllocationRequest(releases=[rel]))
    assert len(cb.releases) == 1
    n = core.schedule_once()
    assert n == 1


def test_sibling_queues_respect_parent_quota_same_cycle():
    cache, cb, core = make_core(nodes=4, node_cpu=16000)
    add_app(core, "app-a", "root.parent.childa")
    add_app(core, "app-b", "root.parent.childb")
    core.update_allocation(AllocationRequest(
        asks=[ask_of("app-a", f"a-{i}", cpu=1000, mem=2**20) for i in range(8)]
             + [ask_of("app-b", f"b-{i}", cpu=1000, mem=2**20) for i in range(8)]))
    n = core.schedule_once()
    assert n == 10  # parent max 10 vcore caps the joint admission
    parent = core.queues.resolve("root.parent.childa", create=False).parent
    assert parent.allocated.get("cpu") == 10000


def test_priority_order_wins_scarce_capacity():
    cache, cb, core = make_core(nodes=1, node_cpu=2000)
    add_app(core, "app-1")
    core.update_allocation(AllocationRequest(asks=[
        ask_of("app-1", "low", cpu=2000, priority=0),
        ask_of("app-1", "high", cpu=2000, priority=100),
    ]))
    core.schedule_once()
    assert [a.allocation_key for a in cb.allocations] == ["high"]
    # the loser got an autoscaler SKIPPED update
    assert any(u.allocation_key == "low" for u in cb.container_updates)


def test_drf_fair_share_between_queues():
    # queue A already uses most of the cluster; queue B's asks go first
    cache, cb, core = make_core(nodes=1, node_cpu=4000)
    add_app(core, "app-a", "root.default")
    add_app(core, "app-b", "root.newq")
    core.update_allocation(AllocationRequest(asks=[ask_of("app-a", "a-0", cpu=2000, mem=2**20)]))
    core.schedule_once()
    assert len(cb.allocations) == 1
    # both queues now ask for the remaining 2000m; B (share 0) outranks A
    core.update_allocation(AllocationRequest(asks=[
        ask_of("app-a", "a-1", cpu=2000, mem=2**20),
        ask_of("app-b", "b-0", cpu=2000, mem=2**20),
    ]))
    core.schedule_once()
    winners = [a.allocation_key for a in cb.allocations]
    assert "b-0" in winners and "a-1" not in winners


def test_remove_application_releases_accounting():
    cache, cb, core = make_core()
    add_app(core, "app-1")
    core.update_allocation(AllocationRequest(asks=[ask_of("app-1", "p0", cpu=1000)]))
    core.schedule_once()
    leaf = core.queues.resolve("root.default", create=False)
    assert leaf.allocated.get("cpu") == 1000
    core.update_application(ApplicationRequest(remove=[RemoveApplicationRequest("app-1")]))
    assert leaf.allocated.get("cpu") == 0
    assert core.partition.get_application("app-1") is None


def test_recovery_restores_existing_allocation():
    cache, cb, core = make_core()
    add_app(core, "app-1")
    existing = Allocation(allocation_key="p0", application_id="app-1",
                          node_id="node-0", resource=ResourceBuilder().cpu(2000).pods(1).build())
    core.update_allocation(AllocationRequest(allocations=[existing]))
    leaf = core.queues.resolve("root.default", create=False)
    assert leaf.allocated.get("cpu") == 2000
    app = core.partition.get_application("app-1")
    assert "p0" in app.allocations


def test_foreign_allocation_tracked_as_occupied():
    cache, cb, core = make_core()
    foreign = Allocation(allocation_key="f0", application_id="", node_id="node-0",
                         resource=ResourceBuilder().cpu(3000).build(), foreign=True)
    core.update_allocation(AllocationRequest(allocations=[foreign]))
    assert core.partition.nodes["node-0"].occupied.get("cpu") == 3000
    core.update_allocation(AllocationRequest(releases=[
        AllocationRelease(application_id="", allocation_key="f0")]))
    assert core.partition.nodes["node-0"].occupied.get("cpu") == 0


# ---------------------------------------------------------------------------
# Gang: placeholder replacement + timeout
# ---------------------------------------------------------------------------

def test_placeholder_replacement():
    cache, cb, core = make_core(nodes=2, node_cpu=8000)
    add_app(core, "app-g", gang_scheduling_style="Soft")
    ph_asks = [ask_of("app-g", f"ph-{i}", cpu=1000, placeholder=True,
                      task_group_name="tg-1") for i in range(2)]
    core.update_allocation(AllocationRequest(asks=ph_asks))
    core.schedule_once()
    assert len(cb.allocations) == 2
    ph_nodes = {a.allocation_key: a.node_id for a in cb.allocations}
    # real task arrives for the same group
    core.update_allocation(AllocationRequest(asks=[
        ask_of("app-g", "real-0", cpu=1000, task_group_name="tg-1")]))
    core.schedule_once()
    real = [a for a in cb.allocations if a.allocation_key == "real-0"]
    assert len(real) == 1
    assert real[0].node_id in ph_nodes.values()  # landed on a placeholder node
    released = [r for r in cb.releases if r.termination_type == TerminationType.PLACEHOLDER_REPLACED]
    assert len(released) == 1


def test_placeholder_timeout_soft_resumes():
    cache, cb, core = make_core()
    add_app(core, "app-g", gang_scheduling_style="Soft", execution_timeout_seconds=0.05)
    core.update_allocation(AllocationRequest(asks=[
        ask_of("app-g", "ph-0", cpu=1000, placeholder=True, task_group_name="tg-1")]))
    core.schedule_once()
    assert len(cb.allocations) == 1
    time.sleep(0.1)
    core.schedule_once()  # first cycle marks reserving_since... already set on alloc cycle
    time.sleep(0.1)
    core.schedule_once()
    resumed = [u for u in cb.updated_apps if u.state == "Resuming"]
    assert resumed and resumed[0].application_id == "app-g"
    timeout_rel = [r for r in cb.releases if r.termination_type == TerminationType.TIMEOUT]
    assert timeout_rel


def test_placeholder_timeout_hard_fails():
    cache, cb, core = make_core()
    add_app(core, "app-h", gang_scheduling_style="Hard", execution_timeout_seconds=0.05)
    core.update_allocation(AllocationRequest(asks=[
        ask_of("app-h", "ph-0", cpu=1000, placeholder=True, task_group_name="tg-1")]))
    core.schedule_once()
    time.sleep(0.15)
    core.schedule_once()
    time.sleep(0.05)
    core.schedule_once()
    failing = [u for u in cb.updated_apps if u.state == "Failing"]
    assert failing and failing[0].application_id == "app-h"


def test_validate_configuration():
    cache, cb, core = make_core()
    ok, _ = core.validate_configuration(QUEUES_YAML)
    assert ok
    ok, msg = core.validate_configuration("partitions: [{name: default, queues: [{name: notroot}]}]")
    assert not ok
    ok, msg = core.validate_configuration(":::bad yaml {{{")
    assert not ok


def test_state_dump():
    cache, cb, core = make_core()
    add_app(core, "app-1")
    import json

    dump = json.loads(core.state_dump())
    assert "partition" in dump and "queues" in dump
    assert dump["queues"]["queuename"] == "root"


# ---------------------------------------------------------------------------
# User / group limits (reference user_group_limit e2e suite)
# ---------------------------------------------------------------------------

USER_LIMIT_YAML = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: limited
            limits:
              - users: [alice]
                maxresources: {vcore: 2}
                maxapplications: 2
              - users: ["*"]
                maxresources: {vcore: 4}
          - name: grouplim
            limits:
              - groups: [devs]
                maxresources: {vcore: 1}
"""


def test_user_resource_limit_enforced():
    cache, cb, core = make_core(nodes=2, node_cpu=16000, queues_yaml=USER_LIMIT_YAML)
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="a1", queue_name="root.limited",
                              user=UserGroupInfo(user="alice"))]))
    core.update_allocation(AllocationRequest(
        asks=[ask_of("a1", f"p{i}", cpu=1000, mem=2**20) for i in range(5)]))
    n = core.schedule_once()
    assert n == 2  # alice capped at 2 vcore
    # another user in the same queue gets the wildcard limit (4 vcore)
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="b1", queue_name="root.limited",
                              user=UserGroupInfo(user="bob"))]))
    core.update_allocation(AllocationRequest(
        asks=[ask_of("b1", f"q{i}", cpu=1000, mem=2**20) for i in range(6)]))
    n = core.schedule_once()
    assert n == 4


def test_user_max_applications_enforced():
    cache, cb, core = make_core(queues_yaml=USER_LIMIT_YAML)
    for i in range(3):
        core.update_application(ApplicationRequest(new=[
            AddApplicationRequest(application_id=f"app-{i}", queue_name="root.limited",
                                  user=UserGroupInfo(user="alice"))]))
    assert cb.accepted_apps.count("app-0") == 1
    assert cb.accepted_apps.count("app-1") == 1
    rejected = [a for a, _ in cb.rejected_apps]
    assert "app-2" in rejected  # maxapplications: 2


def test_group_limit_enforced():
    cache, cb, core = make_core(nodes=2, node_cpu=16000, queues_yaml=USER_LIMIT_YAML)
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="g1", queue_name="root.grouplim",
                              user=UserGroupInfo(user="carol", groups=["devs"]))]))
    core.update_allocation(AllocationRequest(
        asks=[ask_of("g1", f"p{i}", cpu=500, mem=2**20) for i in range(4)]))
    n = core.schedule_once()
    assert n == 2  # devs group capped at 1 vcore
    # release frees user budget
    rel = AllocationRelease(application_id="g1",
                            allocation_key=cb.allocations[0].allocation_key,
                            termination_type=TerminationType.STOPPED_BY_RM)
    core.update_allocation(AllocationRequest(releases=[rel]))
    n = core.schedule_once()
    assert n == 1


def test_group_limit_is_aggregate_across_members():
    """A groups: limit caps the GROUP's total, not each member (ugm tracker
    semantics) — two devs may not jointly exceed the 1-vcore group cap."""
    cache, cb, core = make_core(nodes=2, node_cpu=16000, queues_yaml=USER_LIMIT_YAML)
    for u in ("carol", "dave"):
        core.update_application(ApplicationRequest(new=[
            AddApplicationRequest(application_id=f"g-{u}", queue_name="root.grouplim",
                                  user=UserGroupInfo(user=u, groups=["devs"]))]))
    core.update_allocation(AllocationRequest(
        asks=[ask_of("g-carol", f"c{i}", cpu=500, mem=2**20) for i in range(3)]
             + [ask_of("g-dave", f"d{i}", cpu=500, mem=2**20) for i in range(3)]))
    n = core.schedule_once()
    assert n == 2  # 1 vcore total for the devs group, not per user
    leaf = core.queues.resolve("root.grouplim", create=False)
    assert leaf.group_allocated["devs"].get("cpu") == 1000


def test_parent_queue_limit_enforced_across_cycles():
    """Limits on an intermediate parent must count committed usage (not just
    in-cycle overlays) — placements in later cycles respect earlier ones."""
    yaml_text = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: spark
            limits:
              - users: ["*"]
                maxresources: {vcore: 4}
            queues:
              - name: team-a
              - name: team-b
"""
    cache, cb, core = make_core(nodes=2, node_cpu=16000, queues_yaml=yaml_text)
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="a", queue_name="root.spark.team-a",
                              user=UserGroupInfo(user="eve"))]))
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="b", queue_name="root.spark.team-b",
                              user=UserGroupInfo(user="eve"))]))
    core.update_allocation(AllocationRequest(
        asks=[ask_of("a", f"a{i}", cpu=1000, mem=2**20) for i in range(3)]))
    assert core.schedule_once() == 3
    # second cycle, other leaf under the same limited parent: only 1 more fits
    core.update_allocation(AllocationRequest(
        asks=[ask_of("b", f"b{i}", cpu=1000, mem=2**20) for i in range(3)]))
    assert core.schedule_once() == 1


def test_submit_acl_enforced():
    yaml_text = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: open
            submitacl: "*"
          - name: secure
            submitacl: "alice bleague"
"""
    cache, cb, core = make_core(queues_yaml=yaml_text)
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="ok1", queue_name="root.open",
                              user=UserGroupInfo(user="anyone"))]))
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="ok2", queue_name="root.secure",
                              user=UserGroupInfo(user="alice"))]))
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="denied", queue_name="root.secure",
                              user=UserGroupInfo(user="bob", groups=["cleague"]))]))
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="grp", queue_name="root.secure",
                              user=UserGroupInfo(user="carl", groups=["bleague", "league"]))]))
    assert "ok1" in cb.accepted_apps
    assert "ok2" in cb.accepted_apps
    assert "grp" in cb.accepted_apps       # group membership grants
    rejected = [a for a, _ in cb.rejected_apps]
    assert "denied" in rejected            # wrong user, wrong groups


def test_required_node_ask_bypasses_solver():
    """DaemonSet semantics: an ask pinned via preferred_node allocates on
    exactly that node (or stays pending when it cannot fit)."""
    cache, cb, core = make_core(nodes=3, node_cpu=4000)
    add_app(core, "ds-app")
    pinned = ask_of("ds-app", "ds-pod", cpu=1000, mem=2**20)
    pinned.preferred_node = "node-2"
    core.update_allocation(AllocationRequest(asks=[pinned]))
    core.schedule_once()
    allocs = {a.allocation_key: a.node_id for a in cb.allocations}
    assert allocs["ds-pod"] == "node-2"
    # pinned to a full node: stays pending
    filler = [ask_of("ds-app", f"f{i}", cpu=1000, mem=2**20) for i in range(12)]
    core.update_allocation(AllocationRequest(asks=filler))
    core.schedule_once()
    stuck = ask_of("ds-app", "stuck", cpu=4000, mem=2**20)
    stuck.preferred_node = "node-0"
    core.update_allocation(AllocationRequest(asks=[stuck]))
    core.schedule_once()
    assert "stuck" not in {a.allocation_key for a in cb.allocations}
    assert "stuck" in core.partition.get_application("ds-app").pending_asks


def test_priority_offset_and_fence():
    yaml_text = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: boosted
            properties: {"priority.offset": "100"}
          - name: fenced
            properties: {"priority.offset": "5", "priority.policy": "fence"}
"""
    cache, cb, core = make_core(nodes=1, node_cpu=1000, queues_yaml=yaml_text)
    boosted = core.queues.resolve("root.boosted", create=False)
    fenced = core.queues.resolve("root.fenced", create=False)
    assert boosted.priority_adjustment() == 100
    assert fenced.priority_adjustment() == 5  # fence stops above itself
    # within the boosted queue, adjusted priority orders asks the same way
    add_app(core, "b-app", "root.boosted")
    core.update_allocation(AllocationRequest(asks=[
        ask_of("b-app", "low", cpu=1000, priority=0),
        ask_of("b-app", "high", cpu=1000, priority=50),
    ]))
    core.schedule_once()
    assert [a.allocation_key for a in cb.allocations] == ["high"]


def test_priority_offset_boosts_across_queues():
    """The offset must matter ACROSS queues: a boosted queue's asks win
    scarce capacity over a plain queue's equal-priority asks."""
    yaml_text = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: boosted
            properties: {"priority.offset": "100"}
          - name: normal
"""
    cache, cb, core = make_core(nodes=1, node_cpu=1000, queues_yaml=yaml_text)
    add_app(core, "n-app", "root.normal")
    add_app(core, "b-app", "root.boosted")
    core.update_allocation(AllocationRequest(asks=[
        ask_of("n-app", "n0", cpu=1000, priority=0),
        ask_of("b-app", "b0", cpu=1000, priority=0),
    ]))
    core.schedule_once()
    assert [a.allocation_key for a in cb.allocations] == ["b0"]


def test_resuming_app_completes():
    """A Soft-gang app that resumed (placeholders timed out) and finished its
    real work must complete, not leak (review regression)."""
    cache, cb, core = make_core()
    core._completing_timeout = 0.05
    add_app(core, "res-app", gang_scheduling_style="Soft", execution_timeout_seconds=0.05)
    core.update_allocation(AllocationRequest(asks=[
        ask_of("res-app", "ph-0", cpu=1000, placeholder=True, task_group_name="tg")]))
    core.schedule_once()
    time.sleep(0.15)
    core.schedule_once()  # timeout fires → Resuming, placeholders released
    app = core.partition.get_application("res-app")
    assert app.state == "Resuming"
    time.sleep(0.1)
    core.schedule_once()  # nothing left → Completing → Completed
    time.sleep(0.1)
    core.schedule_once()
    completed = [u for u in cb.updated_apps if u.state == "Completed"]
    assert completed and completed[0].application_id == "res-app"


def test_placement_rules_and_namespace_quota():
    import json as _json

    from yunikorn_tpu.common import constants as C

    cache, cb, core = make_core(nodes=2, node_cpu=16000)
    # no queue provided; namespace tag + parent-queue tag place the app
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(
            application_id="placed", queue_name="",
            user=UserGroupInfo(user="u"),
            tags={C.APP_TAG_NAMESPACE: "team1",
                  C.APP_TAG_NAMESPACE_PARENT_QUEUE: "eng",
                  C.NAMESPACE_QUOTA: _json.dumps({"cpu": "2", "memory": "4Gi"}),
                  C.NAMESPACE_MAX_APPS: "1"})]))
    assert "placed" in cb.accepted_apps
    app = core.partition.get_application("placed")
    assert app.queue_name == "root.eng.team1"
    leaf = core.queues.resolve("root.eng.team1", create=False)
    assert leaf.config.max_resource.get("cpu") == 2000
    # namespace quota enforced on allocations
    core.update_allocation(AllocationRequest(
        asks=[ask_of("placed", f"p{i}", cpu=1000, mem=2**20) for i in range(4)]))
    assert core.schedule_once() == 2
    # namespace.maxApps: second app in the same queue rejected
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(
            application_id="too-many", queue_name="",
            user=UserGroupInfo(user="u"),
            tags={C.APP_TAG_NAMESPACE: "team1",
                  C.APP_TAG_NAMESPACE_PARENT_QUEUE: "eng"})]))
    rejected = [a for a, _ in cb.rejected_apps]
    assert "too-many" in rejected


def test_default_namespace_placement():
    cache, cb, core = make_core()
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="ns-app", queue_name="",
                              user=UserGroupInfo(user="u"),
                              tags={"namespace": "batch"})]))
    assert core.partition.get_application("ns-app").queue_name == "root.batch"


# ---------------------------------------------------------------------------
# Round-2 advisor regressions
# ---------------------------------------------------------------------------

def test_placeholder_replacement_requires_fit():
    """A real ask larger than its placeholder (plus the node's free) must NOT
    replace it — yunikorn-core's tryPlaceholderAllocate only replaces when the
    real allocation fits; anything else silently overcommits the node."""
    cache, cb, core = make_core(nodes=1, node_cpu=4000)
    add_app(core, "app-g", gang_scheduling_style="Soft")
    core.update_allocation(AllocationRequest(asks=[
        ask_of("app-g", "ph-0", cpu=1000, placeholder=True, task_group_name="tg-1")]))
    core.schedule_once()
    assert len(cb.allocations) == 1
    # fill the node: free is now 0
    add_app(core, "filler")
    core.update_allocation(AllocationRequest(asks=[
        ask_of("filler", "f0", cpu=3000, mem=2**20)]))
    core.schedule_once()
    # real ask needs 2000 > placeholder 1000 + free 0 → must stay pending
    core.update_allocation(AllocationRequest(asks=[
        ask_of("app-g", "real-0", cpu=2000, task_group_name="tg-1")]))
    core.schedule_once()
    assert "real-0" not in {a.allocation_key for a in cb.allocations}
    assert not any(r.termination_type == TerminationType.PLACEHOLDER_REPLACED
                   for r in cb.releases)
    app = core.partition.get_application("app-g")
    assert "real-0" in app.pending_asks
    assert "ph-0" in app.allocations  # placeholder kept
    total = sum(a.resource.get("cpu") for app2 in
                core.partition.applications.values()
                for a in app2.allocations.values())
    assert total <= 4000  # no oversubscription


def test_placeholder_replacement_fits_with_node_free():
    """A real ask larger than the placeholder alone but within placeholder +
    node free is still a valid replacement."""
    cache, cb, core = make_core(nodes=1, node_cpu=4000)
    add_app(core, "app-g", gang_scheduling_style="Soft")
    core.update_allocation(AllocationRequest(asks=[
        ask_of("app-g", "ph-0", cpu=1000, placeholder=True, task_group_name="tg-1")]))
    core.schedule_once()
    add_app(core, "filler")
    core.update_allocation(AllocationRequest(asks=[
        ask_of("filler", "f0", cpu=1000, mem=2**20)]))
    core.schedule_once()
    # free = 2000; real 2000 ≤ ph 1000 + free 2000 → replace
    core.update_allocation(AllocationRequest(asks=[
        ask_of("app-g", "real-0", cpu=2000, task_group_name="tg-1")]))
    core.schedule_once()
    assert "real-0" in {a.allocation_key for a in cb.allocations}
    assert any(r.termination_type == TerminationType.PLACEHOLDER_REPLACED
               for r in cb.releases)


def test_required_node_ask_respects_queue_quota():
    """Pinned (RequiredNode/DaemonSet) asks are still subject to queue
    headroom — yunikorn-core gates required-node asks on headroom too."""
    cache, cb, core = make_core(nodes=2, node_cpu=16000)
    add_app(core, "ds-app", "root.limited")  # max 2 vcore
    pinned = ask_of("ds-app", "big-ds", cpu=3000, mem=2**20)
    pinned.preferred_node = "node-0"
    core.update_allocation(AllocationRequest(asks=[pinned]))
    core.schedule_once()
    assert "big-ds" not in {a.allocation_key for a in cb.allocations}
    assert "big-ds" in core.partition.get_application("ds-app").pending_asks
    # within quota: allocates on the pinned node
    ok = ask_of("ds-app", "small-ds", cpu=1000, mem=2**20)
    ok.preferred_node = "node-0"
    core.update_allocation(AllocationRequest(asks=[ok]))
    core.schedule_once()
    allocs = {a.allocation_key: a.node_id for a in cb.allocations}
    assert allocs.get("small-ds") == "node-0"


def test_foreign_allocation_update_does_not_double_count():
    """Re-sending a foreign allocation (resource change or node move) must
    replace the tracked entry, not accumulate occupied forever."""
    cache, cb, core = make_core(nodes=2)
    f = Allocation(allocation_key="f0", application_id="", node_id="node-0",
                   resource=ResourceBuilder().cpu(3000).build(), foreign=True)
    core.update_allocation(AllocationRequest(allocations=[f]))
    assert core.partition.nodes["node-0"].occupied.get("cpu") == 3000
    # resource shrink in place
    f2 = Allocation(allocation_key="f0", application_id="", node_id="node-0",
                    resource=ResourceBuilder().cpu(2000).build(), foreign=True)
    core.update_allocation(AllocationRequest(allocations=[f2]))
    assert core.partition.nodes["node-0"].occupied.get("cpu") == 2000
    # move to the other node
    f3 = Allocation(allocation_key="f0", application_id="", node_id="node-1",
                    resource=ResourceBuilder().cpu(2000).build(), foreign=True)
    core.update_allocation(AllocationRequest(allocations=[f3]))
    assert core.partition.nodes["node-0"].occupied.get("cpu") == 0
    assert core.partition.nodes["node-1"].occupied.get("cpu") == 2000


# ---------------------------------------------------------------------------
# Placement rules + multi-partition (round-2)
# ---------------------------------------------------------------------------

PLACEMENT_YAML = """
partitions:
  - name: default
    placementrules:
      - name: user
        filter:
          type: allow
          users: [admin]
      - name: group
        parent:
          name: fixed
          value: root.teams
        filter:
          type: allow
          groups: [devs]
      - name: tag
        value: namespace
    queues:
      - name: root
        queues:
          - name: default
"""


def _add_app_user(core, app_id, user, groups=(), queue="", tags=None):
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id=app_id, queue_name=queue,
                              user=UserGroupInfo(user=user, groups=list(groups)),
                              tags=dict(tags or {}))]))


def test_placement_rule_user_routes_to_user_queue():
    cache, cb, core = make_core(queues_yaml=PLACEMENT_YAML)
    _add_app_user(core, "app-a", "admin")
    assert core.partition.get_application("app-a").queue_name == "root.admin"


def test_placement_rule_chain_fallthrough_and_filters():
    cache, cb, core = make_core(queues_yaml=PLACEMENT_YAML)
    # not admin → user rule filtered out; in devs → group rule with parent
    _add_app_user(core, "app-b", "bob", groups=["devs"])
    assert core.partition.get_application("app-b").queue_name == "root.teams.devs"
    # neither → tag rule places by namespace
    _add_app_user(core, "app-c", "carol", tags={"namespace": "batch"})
    assert core.partition.get_application("app-c").queue_name == "root.batch"
    # no rule matches at all → rejected
    _add_app_user(core, "app-d", "dave")
    assert core.partition.get_application("app-d") is None
    assert any(a == "app-d" for a, _ in cb.rejected_apps)


def test_placement_rule_sanitizes_dotted_user():
    yaml_text = """
partitions:
  - name: default
    placementrules:
      - name: user
    queues:
      - name: root
"""
    cache, cb, core = make_core(queues_yaml=yaml_text)
    _add_app_user(core, "app-e", "jane.doe")
    assert core.partition.get_application("app-e").queue_name == "root.jane_dot_doe"


MULTI_PARTITION_YAML = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: default
  - name: gpu
    nodesortpolicy:
      type: fair
    queues:
      - name: root
        queues:
          - name: default
          - name: capped
            resources:
              max: {vcore: 1}
"""


def test_second_partition_schedules_independently():
    cache = SchedulerCache()
    cb = RecordingCallback()
    core = CoreScheduler(cache)
    core.register_resource_manager(RegisterResourceManagerRequest(
        rm_id="rm-1", policy_group="queues", config=MULTI_PARTITION_YAML), cb)
    infos = []
    for i in range(2):
        n = make_node(f"cpu-{i}", cpu_milli=8000)
        cache.update_node(n)
        infos.append(NodeInfo(node_id=n.name, action=NodeAction.CREATE))
    for i in range(2):
        n = make_node(f"gpu-{i}", cpu_milli=8000)
        cache.update_node(n)
        infos.append(NodeInfo(node_id=n.name, action=NodeAction.CREATE,
                              attributes={"si/node-partition": "gpu"}))
    core.update_node(NodeRequest(nodes=infos))
    assert set(core.partitions) == {"default", "gpu"}
    assert set(core.partitions["gpu"].nodes) == {"gpu-0", "gpu-1"}

    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="cpu-app", queue_name="root.default",
                              user=UserGroupInfo(user="u")),
        AddApplicationRequest(application_id="gpu-app", queue_name="root.default",
                              partition="gpu", user=UserGroupInfo(user="u")),
    ]))
    asks = [ask_of("cpu-app", f"c{i}") for i in range(4)]
    asks += [ask_of("gpu-app", f"g{i}") for i in range(4)]
    core.update_allocation(AllocationRequest(asks=asks))
    core.schedule_once()
    by_key = {a.allocation_key: a.node_id for a in cb.allocations}
    assert len(by_key) == 8
    for i in range(4):
        assert by_key[f"c{i}"].startswith("cpu-")
        assert by_key[f"g{i}"].startswith("gpu-")


def test_partition_quota_independent():
    cache = SchedulerCache()
    cb = RecordingCallback()
    core = CoreScheduler(cache)
    core.register_resource_manager(RegisterResourceManagerRequest(
        rm_id="rm-1", policy_group="queues", config=MULTI_PARTITION_YAML), cb)
    n = make_node("gpu-0", cpu_milli=16000)
    cache.update_node(n)
    core.update_node(NodeRequest(nodes=[NodeInfo(
        node_id="gpu-0", action=NodeAction.CREATE,
        attributes={"si/node-partition": "gpu"})]))
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="capped-app", queue_name="root.capped",
                              partition="gpu", user=UserGroupInfo(user="u"))]))
    asks = [ask_of("capped-app", f"p{i}", cpu=1000) for i in range(3)]
    core.update_allocation(AllocationRequest(asks=asks))
    n_alloc = core.schedule_once()
    assert n_alloc == 1  # gpu partition's root.capped max 1 vcore


def test_partition_removed_from_config_drains():
    cache = SchedulerCache()
    cb = RecordingCallback()
    core = CoreScheduler(cache)
    core.register_resource_manager(RegisterResourceManagerRequest(
        rm_id="rm-1", policy_group="queues", config=MULTI_PARTITION_YAML), cb)
    n = make_node("gpu-0", cpu_milli=8000)
    cache.update_node(n)
    core.update_node(NodeRequest(nodes=[NodeInfo(
        node_id="gpu-0", action=NodeAction.CREATE,
        attributes={"si/node-partition": "gpu"})]))
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="g-app", queue_name="root.default",
                              partition="gpu", user=UserGroupInfo(user="u"))]))
    assert core.partitions["gpu"].get_application("g-app") is not None
    # reload config WITHOUT the gpu partition → drains (nodes still present)
    single = """
partitions:
  - name: default
    queues:
      - name: root
        queues: [{name: default}]
"""
    core.update_configuration(single, {})
    assert core.partitions["gpu"].draining
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id="late-app", queue_name="root.default",
                              partition="gpu", user=UserGroupInfo(user="u"))]))
    assert any(a == "late-app" for a, _ in cb.rejected_apps)
    # no new scheduling in the drained partition
    core.update_allocation(AllocationRequest(asks=[ask_of("g-app", "g0")]))
    assert core.schedule_once() == 0


def test_duplicate_node_across_partitions_rejected():
    cache = SchedulerCache()
    cb = RecordingCallback()
    core = CoreScheduler(cache)
    core.register_resource_manager(RegisterResourceManagerRequest(
        rm_id="rm-1", policy_group="queues", config=MULTI_PARTITION_YAML), cb)
    n = make_node("n0", cpu_milli=8000)
    cache.update_node(n)
    core.update_node(NodeRequest(nodes=[NodeInfo(node_id="n0", action=NodeAction.CREATE)]))
    core.update_node(NodeRequest(nodes=[NodeInfo(
        node_id="n0", action=NodeAction.CREATE,
        attributes={"si/node-partition": "gpu"})]))
    assert "n0" in core.partitions["default"].nodes
    assert "n0" not in core.partitions["gpu"].nodes


def test_foreign_move_across_partitions_releases_old_entry():
    """A foreign pod re-sent on a node in a DIFFERENT partition must drop the
    old partition's tracked entry and decrement that node's occupied
    (ADVICE r2: _track_foreign searched only the new partition)."""
    cache = SchedulerCache()
    cb = RecordingCallback()
    core = CoreScheduler(cache)
    core.register_resource_manager(RegisterResourceManagerRequest(
        rm_id="rm-1", policy_group="queues", config=MULTI_PARTITION_YAML), cb)
    infos = []
    for name, part in (("cpu-0", ""), ("gpu-0", "gpu")):
        n = make_node(name, cpu_milli=8000)
        cache.update_node(n)
        attrs = {"si/node-partition": part} if part else {}
        infos.append(NodeInfo(node_id=name, action=NodeAction.CREATE, attributes=attrs))
    core.update_node(NodeRequest(nodes=infos))

    f = Allocation(allocation_key="f0", application_id="", node_id="cpu-0",
                   resource=ResourceBuilder().cpu(3000).build(), foreign=True)
    core.update_allocation(AllocationRequest(allocations=[f]))
    assert core.partitions["default"].nodes["cpu-0"].occupied.get("cpu") == 3000
    # the pod moves onto a gpu-partition node
    f2 = Allocation(allocation_key="f0", application_id="", node_id="gpu-0",
                    resource=ResourceBuilder().cpu(3000).build(), foreign=True)
    core.update_allocation(AllocationRequest(allocations=[f2]))
    assert core.partitions["default"].nodes["cpu-0"].occupied.get("cpu") == 0
    assert "f0" not in core.partitions["default"].foreign_allocations
    assert core.partitions["gpu"].nodes["gpu-0"].occupied.get("cpu") == 3000
    # release finds it exactly once
    core.update_allocation(AllocationRequest(releases=[
        AllocationRelease(application_id="", allocation_key="f0")]))
    assert core.partitions["gpu"].nodes["gpu-0"].occupied.get("cpu") == 0


def test_partition_capacity_memo_invalidated_by_membership_change():
    """Node registration into a partition changes its capacity without a
    cache capacity_version bump in between (the cache saw the node before
    the memo was computed) — the memo must still invalidate (ADVICE r2)."""
    cache = SchedulerCache()
    cb = RecordingCallback()
    core = CoreScheduler(cache)
    core.register_resource_manager(RegisterResourceManagerRequest(
        rm_id="rm-1", policy_group="queues", config=MULTI_PARTITION_YAML), cb)
    n0 = make_node("gpu-0", cpu_milli=8000)
    cache.update_node(n0)
    core.update_node(NodeRequest(nodes=[NodeInfo(
        node_id="gpu-0", action=NodeAction.CREATE,
        schedulable_resource=ResourceBuilder().cpu(8000).build(),
        attributes={"si/node-partition": "gpu"})]))
    # second node lands in the CACHE first (capacity_version bumps here) ...
    n1 = make_node("gpu-1", cpu_milli=8000)
    cache.update_node(n1)
    core._use_partition("gpu")
    cap_before = core._cluster_capacity()   # memoized at the current versions
    assert cap_before.get("cpu") == 8000    # gpu-1 not yet registered in core
    # ... then registers at the core with NO further cache version bump
    core.update_node(NodeRequest(nodes=[NodeInfo(
        node_id="gpu-1", action=NodeAction.CREATE,
        schedulable_resource=ResourceBuilder().cpu(8000).build(),
        attributes={"si/node-partition": "gpu"})]))
    core._use_partition("gpu")
    assert core._cluster_capacity().get("cpu") == 16000


# ---------------------------------------------------------------------------
# Locality-fallback drain: overflow groups schedule in intra-cycle rounds
# (round-2 behavior was one pod per group per CYCLE — a silent 1000x cliff)
# ---------------------------------------------------------------------------

def _overflow_anti_ask(app_id, name, n_terms=7):
    """Mutually anti-affine pods whose term count overflows the tensor
    encoding (MAX_CONSTRAINT_SLOTS=6): must take the exact host path."""
    from yunikorn_tpu.common.objects import Affinity, PodAffinityTerm

    pod = make_pod(name, cpu_milli=100, memory=2**20, labels={"x0": "t"})
    pod.spec.affinity = Affinity(pod_anti_affinity_required=[
        PodAffinityTerm(label_selector={"matchLabels": {f"x{i}": "t"}},
                        topology_key="kubernetes.io/hostname")
        for i in range(n_terms)
    ])
    return AllocationAsk(allocation_key=name, application_id=app_id,
                         resource=get_pod_resource(pod), pod=pod)


def test_locality_fallback_drains_whole_group_in_one_cycle():
    cache, cb, core = make_core(nodes=8)
    add_app(core, "app-fb")
    asks = [_overflow_anti_ask("app-fb", f"fb-{i}") for i in range(6)]
    core.update_allocation(AllocationRequest(asks=asks))
    n = core.schedule_once()
    # ALL six land in ONE cycle (main solve places 1, drain rounds the rest)
    assert n == 6
    by_key = {a.allocation_key: a.node_id for a in cb.allocations}
    assert len(by_key) == 6
    # mutual hostname anti-affinity: every pod on a DISTINCT node — proves the
    # drain's extra_placed overlay sees intra-cycle commitments (without it,
    # two drain rounds could stack pods on one node)
    assert len(set(by_key.values())) == 6
    # operator visibility: metric counters + pod events
    assert core.metrics.get("locality_fallback_groups_total", 0) >= 1
    assert core.metrics.get("locality_fallback_deferred_total", 0) == 5
    reasons = {e.reason for e in cb.events}
    assert "LocalityEncodingOverflow" in reasons
    entry = core.metrics["last_cycle"]["default"]
    assert entry["fallback_placed"] == 5 and entry["fallback_rounds"] >= 5


def test_locality_fallback_rounds_zero_keeps_serialized_behavior():
    from yunikorn_tpu.core.scheduler import SolverOptions

    cache = SchedulerCache()
    cb = RecordingCallback()
    core = CoreScheduler(cache, solver_options=SolverOptions(fallback_rounds=0))
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="rm-1", policy_group="queues",
                                       config=QUEUES_YAML), cb)
    infos = []
    for i in range(4):
        nd = make_node(f"node-{i}", cpu_milli=8000, memory=16 * 2**30)
        cache.update_node(nd)
        infos.append(NodeInfo(node_id=nd.name, action=NodeAction.CREATE,
                              schedulable_resource=ResourceBuilder().cpu(8000).build()))
    core.update_node(NodeRequest(nodes=infos))
    add_app(core, "app-fb0")
    asks = [_overflow_anti_ask("app-fb0", f"z-{i}") for i in range(3)]
    core.update_allocation(AllocationRequest(asks=asks))
    assert core.schedule_once() == 1      # one pod per cycle when disabled
    # the rest remain pending and drain over subsequent cycles; a commit is
    # not yet in the cache, so later cycles rely on the inflight overlay +
    # host mask re-evaluation against extra_placed=None (cache-only state).
    # Simulate the shim's assume so the next cycle's mask sees the placement.
    for a in cb.allocations:
        ask = next(x for x in asks if x.allocation_key == a.allocation_key)
        ask.pod.spec.node_name = a.node_id
        ask.pod.status.phase = "Running"
        cache.update_pod(ask.pod)
    assert core.schedule_once() == 1
