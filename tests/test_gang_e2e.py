"""Gang scheduling end-to-end (reference test/e2e/gang_scheduling suite model):
placeholder creation, reservation, all-bound → Running, replacement, Soft/Hard
timeout semantics, placeholder cleanup.
"""
import json
import time

import pytest

from yunikorn_tpu.cache import application as app_mod
from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.shim.mock_scheduler import MockScheduler


@pytest.fixture
def sched():
    ms = MockScheduler()
    ms.init("")
    ms.start()
    yield ms
    ms.stop()


def gang_pod(name, app_id, task_groups, tg_name="", cpu=500,
             timeout_s=None, style=None):
    annotations = {constants.ANNOTATION_TASK_GROUPS: json.dumps(task_groups)}
    if tg_name:
        annotations[constants.ANNOTATION_TASK_GROUP_NAME] = tg_name
    params = []
    if timeout_s is not None:
        params.append(f"{constants.SCHED_POLICY_TIMEOUT_PARAM}={timeout_s}")
    if style is not None:
        params.append(f"{constants.SCHED_POLICY_STYLE_PARAM}={style}")
    if params:
        annotations[constants.ANNOTATION_SCHED_POLICY_PARAM] = \
            constants.SCHED_POLICY_PARAM_DELIMITER.join(params)
    return make_pod(
        name,
        cpu_milli=cpu,
        memory=2**28,
        labels={constants.LABEL_APPLICATION_ID: app_id},
        annotations=annotations,
        scheduler_name=constants.SCHEDULER_NAME,
    )


TG = [{"name": "workers", "minMember": 3,
       "minResource": {"cpu": "500m", "memory": "256Mi"}}]


def count_placeholders(sched, app_id):
    return sum(1 for p in sched.cluster.list_pods()
               if p.metadata.annotations.get(constants.ANNOTATION_PLACEHOLDER_FLAG)
               == constants.TRUE
               and p.metadata.labels.get(constants.LABEL_APPLICATION_ID) == app_id)


def test_gang_reserve_then_run(sched):
    sched.add_nodes([make_node(f"n{i}", cpu_milli=4000) for i in range(2)])
    origin = gang_pod("driver", "gang-1", TG, tg_name="", cpu=500)
    sched.add_pod(origin)
    # app goes Reserving and creates minMember placeholders
    sched.wait_for_app_state("gang-1", app_mod.RUNNING, timeout=15)
    assert count_placeholders(sched, "gang-1") == 3
    # originator (non-placeholder, no task group) is bound after gang is up
    sched.wait_for_task_state("gang-1", origin.uid, task_mod.BOUND)


def test_gang_replacement(sched):
    sched.add_nodes([make_node(f"n{i}", cpu_milli=4000) for i in range(2)])
    origin = gang_pod("driver", "gang-2", TG)
    sched.add_pod(origin)
    sched.wait_for_app_state("gang-2", app_mod.RUNNING, timeout=15)
    # real member pods arrive tagged with the task group
    members = [gang_pod(f"worker-{i}", "gang-2", TG, tg_name="workers")
               for i in range(3)]
    ph_nodes = {p.spec.node_name for p in sched.cluster.list_pods()
                if p.metadata.annotations.get(constants.ANNOTATION_PLACEHOLDER_FLAG)}
    for m in members:
        sched.add_pod(m)
    for m in members:
        sched.wait_for_task_state("gang-2", m.uid, task_mod.BOUND, timeout=15)
        assert sched.get_pod_assignment(m) in ph_nodes
    # placeholders replaced and deleted from the cluster
    deadline = time.time() + 10
    while time.time() < deadline and count_placeholders(sched, "gang-2") > 0:
        time.sleep(0.05)
    assert count_placeholders(sched, "gang-2") == 0


def test_gang_soft_timeout_falls_back(sched):
    # placeholders can never fit (huge minResource) → timeout → Soft: Resuming → Running
    sched.add_node(make_node("n0", cpu_milli=2000))
    big_tg = [{"name": "big", "minMember": 2,
               "minResource": {"cpu": "100", "memory": "1Gi"}}]
    origin = gang_pod("driver", "gang-soft", big_tg, cpu=500,
                      timeout_s=1, style="Soft")
    sched.add_pod(origin)
    # app eventually runs without the gang (Soft fallback)
    sched.wait_for_app_state("gang-soft", app_mod.RUNNING, timeout=20)
    sched.wait_for_task_state("gang-soft", origin.uid, task_mod.BOUND, timeout=15)


def test_gang_hard_timeout_fails_app(sched):
    sched.add_node(make_node("n0", cpu_milli=2000))
    big_tg = [{"name": "big", "minMember": 2,
               "minResource": {"cpu": "100", "memory": "1Gi"}}]
    origin = gang_pod("driver", "gang-hard", big_tg, cpu=500,
                      timeout_s=1, style="Hard")
    sched.add_pod(origin)
    deadline = time.time() + 20
    seen_failing = False
    existed = False
    while time.time() < deadline:
        app = sched.context.get_application("gang-hard")
        if app is not None:
            existed = True
            if app.state in (app_mod.FAILING, app_mod.FAILED):
                seen_failing = True
                break
        elif existed:
            # app failed and was garbage-collected by the pump — also a pass
            seen_failing = True
            break
        time.sleep(0.05)
    assert seen_failing


def test_gang_disabled_by_conf():
    ms = MockScheduler()
    ms.init("")
    from yunikorn_tpu.conf.schedulerconf import get_holder

    get_holder().get().disable_gang_scheduling = True
    ms.context.conf.disable_gang_scheduling = True
    ms.start()
    try:
        ms.add_node(make_node("n0", cpu_milli=4000))
        origin = gang_pod("driver", "nogang", TG)
        ms.add_pod(origin)
        ms.wait_for_task_state("nogang", origin.uid, task_mod.BOUND, timeout=15)
        assert count_placeholders(ms, "nogang") == 0
    finally:
        ms.stop()


def test_placeholder_spec_copies_constraints():
    from yunikorn_tpu.cache.placeholder import gen_placeholder_name, new_placeholder
    from yunikorn_tpu.common.si import TaskGroup

    class FakeApp:
        application_id = "app-x"
        queue_name = "root.q"

        class metadata:
            owner_references = [{"kind": "Pod", "name": "o"}]

    tg = TaskGroup(name="tg1", min_member=2,
                   min_resource={"cpu": "1", "memory": "1Gi"},
                   node_selector={"zone": "a"},
                   tolerations=[{"key": "k", "operator": "Equal", "value": "v",
                                 "effect": "NoSchedule"}])
    name = gen_placeholder_name("app-x", "tg1")
    assert name.startswith("tg-app-x-tg1-") and len(name.split("-")[-1]) == 10
    pod = new_placeholder(name, FakeApp, tg, None)
    assert pod.spec.node_selector == {"zone": "a"}
    assert pod.spec.tolerations[0].key == "k"
    assert pod.spec.scheduler_name == constants.SCHEDULER_NAME
    assert pod.metadata.annotations[constants.ANNOTATION_PLACEHOLDER_FLAG] == constants.TRUE
    from yunikorn_tpu.common.resource import get_pod_resource

    r = get_pod_resource(pod)
    assert r.get("cpu") == 1000 and r.get("memory") == 2**30


def test_gang_multiple_task_groups(sched):
    """Two task groups with different shapes: placeholders per group, members
    replace within THEIR group's placeholders only (reference multi-taskgroup
    gang e2e)."""
    sched.add_nodes([make_node(f"mn{i}", cpu_milli=8000, memory=8 * 2**30)
                     for i in range(3)])
    tgs = [{"name": "drivers", "minMember": 1,
            "minResource": {"cpu": "1", "memory": "512Mi"}},
           {"name": "workers", "minMember": 4,
            "minResource": {"cpu": "500m", "memory": "256Mi"}}]
    origin = gang_pod("origin", "gang-multi", tgs, cpu=200)
    sched.add_pod(origin)
    sched.wait_for_app_state("gang-multi", app_mod.RUNNING, timeout=20)
    assert count_placeholders(sched, "gang-multi") == 5

    def ph_by_group(group):
        return {p.spec.node_name for p in sched.cluster.list_pods()
                if p.metadata.annotations.get(constants.ANNOTATION_PLACEHOLDER_FLAG)
                and p.metadata.annotations.get(
                    constants.ANNOTATION_TASK_GROUP_NAME) == group}

    driver_nodes = ph_by_group("drivers")
    worker_nodes = ph_by_group("workers")
    assert driver_nodes and worker_nodes
    # a drivers member lands on a drivers placeholder node
    d = gang_pod("driver-0", "gang-multi", tgs, tg_name="drivers", cpu=1000)
    sched.add_pod(d)
    sched.wait_for_task_state("gang-multi", d.uid, task_mod.BOUND, timeout=15)
    assert sched.get_pod_assignment(d) in driver_nodes
    # and all workers land within the workers placeholder set
    workers = [gang_pod(f"wk-{i}", "gang-multi", tgs, tg_name="workers")
               for i in range(4)]
    for w in workers:
        sched.add_pod(w)
    for w in workers:
        sched.wait_for_task_state("gang-multi", w.uid, task_mod.BOUND, timeout=15)
        assert sched.get_pod_assignment(w) in worker_nodes
    deadline = time.time() + 10
    while time.time() < deadline and count_placeholders(sched, "gang-multi") > 0:
        time.sleep(0.05)
    assert count_placeholders(sched, "gang-multi") == 0


def test_gang_extra_members_beyond_min(sched):
    """Members beyond minMember (burst past the gang floor) schedule through
    the normal path once placeholders are exhausted."""
    sched.add_nodes([make_node(f"xn{i}", cpu_milli=8000) for i in range(2)])
    tgs = [{"name": "workers", "minMember": 2,
            "minResource": {"cpu": "500m", "memory": "256Mi"}}]
    origin = gang_pod("origin", "gang-extra", tgs, cpu=200)
    sched.add_pod(origin)
    sched.wait_for_app_state("gang-extra", app_mod.RUNNING, timeout=20)
    members = [gang_pod(f"xw-{i}", "gang-extra", tgs, tg_name="workers")
               for i in range(5)]                 # 3 beyond the floor
    for m in members:
        sched.add_pod(m)
    for m in members:
        sched.wait_for_task_state("gang-extra", m.uid, task_mod.BOUND, timeout=20)
    assert count_placeholders(sched, "gang-extra") == 0


def test_gang_app_completion_cleans_leftover_placeholders(sched):
    """Fewer members than minMember arrive and the app finishes: leftover
    placeholders must be deleted, their resources freed (reference
    placeholder_manager cleanUp)."""
    sched.add_nodes([make_node(f"cn{i}", cpu_milli=4000) for i in range(2)])
    tgs = [{"name": "workers", "minMember": 3,
            "minResource": {"cpu": "500m", "memory": "256Mi"}}]
    origin = gang_pod("origin", "gang-clean", tgs, cpu=200)
    sched.add_pod(origin)
    sched.wait_for_app_state("gang-clean", app_mod.RUNNING, timeout=20)
    assert count_placeholders(sched, "gang-clean") == 3
    one = gang_pod("only-worker", "gang-clean", tgs, tg_name="workers")
    sched.add_pod(one)
    sched.wait_for_task_state("gang-clean", one.uid, task_mod.BOUND, timeout=15)
    # the workload ends: everything real completes
    sched.succeed_pod(one)
    sched.succeed_pod(origin)
    deadline = time.time() + 20
    while time.time() < deadline and count_placeholders(sched, "gang-clean") > 0:
        time.sleep(0.1)
    assert count_placeholders(sched, "gang-clean") == 0
    # capacity released: a full-node pod fits again
    probe = make_pod("probe", cpu_milli=3500,
                     labels={constants.LABEL_APPLICATION_ID: "probe-app"},
                     scheduler_name=constants.SCHEDULER_NAME)
    sched.add_pod(probe)
    sched.wait_for_task_state("probe-app", probe.uid, task_mod.BOUND, timeout=15)
